"""Ring attention — context/sequence parallelism over a mesh axis.

NEW capability vs the reference: the reference has no ring attention, no
context/sequence parallelism anywhere in the tree (SURVEY.md §5
"Long-context / sequence parallelism — Absent"). Long sequences there are
handled only by recompute + pipeline micro-batching. Here sequence
parallelism is first-class (a north-star requirement): activations are
sharded along the sequence dim over mesh axis 'sp', and attention runs as
a ring — each device holds its local Q chunk while K/V chunks rotate
around the ring via `lax.ppermute` (the XLA collective-permute that rides
ICI neighbor links), overlapping each hop with the blockwise-attention
compute of the previous chunk.

Design (blockwise/flash formulation, cf. PAPERS.md Ring Attention):
  - uniform chunking: all devices hold S/sp rows, so the causal structure
    is chunk-granular — a K/V chunk from source rank `src` vs local Q of
    rank `idx` is: fully visible (src < idx), the causal diagonal
    (src == idx), or fully masked (src > idx). No offset-aware kernel is
    needed: the diagonal chunk is exactly ordinary causal attention, so
    the existing Pallas flash kernels (ops/flash_attention.py) are reused
    per ring step; `lax.switch` picks the branch per step since `src`
    depends on the traced `axis_index`.
  - online-softmax merge across ring steps: each step returns the chunk's
    normalized output plus its logsumexp; steps combine with the standard
    (m, w, acc) running-max merge, so logits never materialize globally
    (O(S_local) memory per device).
  - backward is a second ring: dK/dV partial accumulators travel around
    the ring WITH their K/V chunk and arrive home after sp hops, while dQ
    accumulates locally. This replaces a gather of full K/V grads with
    neighbor permutes (the same trick the fwd uses).
  - fully-masked steps still pay the permute (the ring must stay in
    lockstep) but skip all compute. Rank 0 computes only its diagonal —
    the classic contiguous-sharding imbalance; a striped ("zigzag")
    layout is future work.

Layouts: public entry [batch, seq_local, heads, head_dim] (paddle
convention, matches flash_attention). `ring_attention` is the
inside-shard_map form; `sequence_parallel_attention` wraps it in a
partial-manual shard_map over just the sp axis so dp/tp stay in GSPMD
auto mode (composes with the tp-sharded head dim and dp-sharded batch).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import flash_attention as fa

_NEG_INF = -1e30


def _use_pallas(sq, sk, d) -> bool:
    return (fa._pick_block(sq, fa._BLOCK_Q) is not None
            and fa._pick_block(sk, fa._BLOCK_K) is not None
            and d <= 256 and sq == sk)


# ---------------------------------------------------------------------------
# per-chunk forward: (o normalized, lse) both [BH, S, *]
# ---------------------------------------------------------------------------


def _chunk_fwd_jnp(q3, k3, v3, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q3, k3,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bqk,bkd->bqd", (p / l).astype(v3.dtype), v3,
                   preferred_element_type=jnp.float32).astype(q3.dtype)
    return o, (m + jnp.log(l))


def _bh_kernel_shard(fn, n_in, n_out, bh):
    """Mosaic inside the pipeline's partially-manual region: wrap a
    [BH, S, *]-chunk kernel call in a shard_map over the remaining auto
    axes (shared rule: distributed/context.nested_kernel_shard). Row
    attention is independent per BH row, so ANY even partition of dim 0
    is numerically exact — P((dp, tp)) contiguous chunks are used even
    though flattened b-major/h-minor order interleaves them. Returns
    None when no scope is active or BH does not split evenly (caller
    falls back to the auto-partitionable jnp path)."""
    from ..distributed import context as dctx
    from jax.sharding import PartitionSpec as P

    pa = dctx.current_pipeline_auto_axes()
    if pa is None or fa._interpret():
        # CPU interpret mode is plain HLO — auto-partitionable, no nest
        return None
    mesh, axes = pa
    dim0 = tuple(a for a in ("dp", "tp")
                 if a in axes and mesh.shape.get(a, 1) > 1)
    size = 1
    for a in dim0:
        size *= mesh.shape[a]
    if bh % size:
        return None
    spec = P(dim0 if dim0 else None, None, None)
    return dctx.nested_kernel_shard(fn, in_specs=(spec,) * n_in,
                                    out_specs=(spec,) * n_out)


def _chunk_fwd(q3, k3, v3, scale, causal):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    if _use_pallas(sq, sk, d):
        bq = fa._pick_block(sq, fa._BLOCK_Q)
        bk = fa._pick_block(sk, fa._BLOCK_K)
        if causal:
            bq = bk = min(bq, bk)
        nested = _bh_kernel_shard(
            lambda a, b, c: fa._fwd(a, b, c, scale, causal, bq, bk),
            n_in=3, n_out=2, bh=bh)
        if nested is not None:
            return nested(q3, k3, v3)
        if _in_partial_manual():
            return _chunk_fwd_jnp(q3, k3, v3, scale, causal)
        return fa._fwd(q3, k3, v3, scale, causal, bq, bk)
    return _chunk_fwd_jnp(q3, k3, v3, scale, causal)


def _in_partial_manual() -> bool:
    from ..distributed import context as dctx

    return dctx.in_partial_manual_region()


def _chunk_skip(q3, k3, v3, scale):
    bh, sq, d = q3.shape
    return (jnp.zeros((bh, sq, d), q3.dtype),
            jnp.full((bh, sq, 1), _NEG_INF, jnp.float32))


# ---------------------------------------------------------------------------
# per-chunk backward: (dq, dk, dv) given global (lse, delta)
# ---------------------------------------------------------------------------


def _chunk_bwd_jnp(q3, k3, v3, do3, lse, delta, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q3, k3,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, _NEG_INF)
    p = jnp.exp(s - lse)                                   # [BH, sq, sk]
    dv = jnp.einsum("bqk,bqd->bkd", p.astype(do3.dtype), do3,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bqd,bkd->bqk", do3, v3,
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds.astype(k3.dtype), k3,
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bqk,bqd->bkd", ds.astype(q3.dtype), q3,
                    preferred_element_type=jnp.float32)
    return dq, dk, dv


def _chunk_bwd(q3, k3, v3, do3, lse, delta, scale, causal):
    """Returns f32 (dq, dk, dv) for one K/V chunk against local Q."""
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    if _use_pallas(sq, sk, d):
        bq = fa._pick_block(sq, fa._BLOCK_Q)
        bk = fa._pick_block(sk, fa._BLOCK_K)
        if causal:
            bq = bk = min(bq, bk)
        # o3 in res is only used for delta, which we precompute (it is a
        # property of the GLOBAL output row); out_dtype f32 so per-chunk
        # partials don't round before the ring accumulation.
        nested = _bh_kernel_shard(
            lambda q_, k_, v_, do_, lse_, delta_: fa._bwd(
                scale, causal, bq, bk, (q_, k_, v_, None, lse_), do_,
                delta=delta_, out_dtype=jnp.float32),
            n_in=6, n_out=3, bh=bh)
        if nested is not None:
            return nested(q3, k3, v3, do3, lse, delta)
        if _in_partial_manual():
            return _chunk_bwd_jnp(q3, k3, v3, do3, lse, delta, scale,
                                  causal)
        return fa._bwd(scale, causal, bq, bk, (q3, k3, v3, None, lse), do3,
                       delta=delta, out_dtype=jnp.float32)
    return _chunk_bwd_jnp(q3, k3, v3, do3, lse, delta, scale, causal)


# ---------------------------------------------------------------------------
# the ring (inside shard_map over `axis_name`)
# ---------------------------------------------------------------------------


def _ring_shift(xs, axis_name, n):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return tuple(lax.ppermute(x, axis_name, perm) for x in xs)


def _branch(t, idx, sp, causal):
    """0 = skip (masked), 1 = full, 2 = diagonal-causal — for ring step t."""
    if not causal:
        return jnp.int32(1), None
    src = (idx - t) % sp
    return jnp.where(src > idx, 0, jnp.where(src < idx, 1, 2)), src


def _auto_scope(auto_ctx):
    """Re-enter the pipeline_auto_axes scope captured at call time.
    custom_vjp backwards are traced at TRANSPOSE time, long after the
    caller's ``with`` scope exited — so the (mesh, axes) pair rides the
    nondiff args and both fwd and bwd re-enter it around their chunk
    kernels."""
    import contextlib

    from ..distributed import context as dctx

    if auto_ctx is None:
        return contextlib.nullcontext()
    return dctx.pipeline_auto_axes_scope(auto_ctx[0], auto_ctx[1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_mha(q, k, v, causal, scale, axis_name, auto_ctx=None):
    o, _ = _ring_fwd_res(q, k, v, causal, scale, axis_name, auto_ctx)
    return o


def _boundary_f32(dtype) -> bool:
    # XLA:CPU crashes on bf16 collectives inside (nested) manual regions
    # (same bug the pipeline works around, distributed/pipeline.py); TPU
    # keeps native bf16 ring transfers.
    from ..core.place import target_platform

    return target_platform() == "cpu" and dtype == jnp.bfloat16


def _ring_fwd_res(q, k, v, causal, scale, axis_name, auto_ctx=None):
    b, s_loc, h, d = q.shape
    sp = lax.psum(1, axis_name)     # axis size: static int under shard_map
    raise_if_not_static(sp)
    idx = lax.axis_index(axis_name)
    s_val = scale if scale is not None else 1.0 / (d ** 0.5)

    out_dtype = q.dtype
    if _boundary_f32(q.dtype):
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    q3 = fa._reshape_in(q)
    k3 = fa._reshape_in(k)
    v3 = fa._reshape_in(v)
    bh = q3.shape[0]

    m = jnp.full((bh, s_loc, 1), _NEG_INF, jnp.float32)
    w = jnp.zeros((bh, s_loc, 1), jnp.float32)
    acc = jnp.zeros((bh, s_loc, d), jnp.float32)
    k_c, v_c = k3, v3
    with _auto_scope(auto_ctx):
        for t in range(sp):
            br, _ = _branch(t, idx, sp, causal)
            o_t, lse_t = lax.switch(
                br,
                [lambda q_, k_, v_: _chunk_skip(q_, k_, v_, s_val),
                 lambda q_, k_, v_: _chunk_fwd(q_, k_, v_, s_val, False),
                 lambda q_, k_, v_: _chunk_fwd(q_, k_, v_, s_val, True)],
                q3, k_c, v_c)
            m_new = jnp.maximum(m, lse_t)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(lse_t - m_new)
            acc = acc * alpha + o_t.astype(jnp.float32) * beta
            w = w * alpha + beta
            m = m_new
            if t < sp - 1:
                k_c, v_c = _ring_shift((k_c, v_c), axis_name, sp)
    w_safe = jnp.where(w == 0.0, 1.0, w)
    o3 = (acc / w_safe).astype(q.dtype)
    lse = m + jnp.log(w_safe)
    o = fa._reshape_out(o3, b, h).astype(out_dtype)
    return o, (q3, k3, v3, o3, lse, b, h, s_val)


def _ring_bwd(causal, scale, axis_name, auto_ctx, res, do):
    q3, k3, v3, o3, lse, b, h, s_val = res
    sp = lax.psum(1, axis_name)
    raise_if_not_static(sp)
    idx = lax.axis_index(axis_name)
    out_dtype = do.dtype           # cotangent dtype == primal out dtype
    do3 = fa._reshape_in(do.astype(q3.dtype))
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = jnp.zeros_like(q3, jnp.float32)
    dk_c = jnp.zeros_like(k3, jnp.float32)
    dv_c = jnp.zeros_like(v3, jnp.float32)
    k_c, v_c = k3, v3

    def _zero(q_, k_, v_, do_, lse_, delta_):
        return (jnp.zeros_like(q_, jnp.float32),
                jnp.zeros_like(k_, jnp.float32),
                jnp.zeros_like(v_, jnp.float32))

    with _auto_scope(auto_ctx):
        for t in range(sp):
            br, _ = _branch(t, idx, sp, causal)
            dq_t, dk_t, dv_t = lax.switch(
                br,
                [_zero,
                 lambda q_, k_, v_, do_, l_, dl_: _chunk_bwd(
                     q_, k_, v_, do_, l_, dl_, s_val, False),
                 lambda q_, k_, v_, do_, l_, dl_: _chunk_bwd(
                     q_, k_, v_, do_, l_, dl_, s_val, True)],
                q3, k_c, v_c, do3, lse, delta)
            dq = dq + dq_t
            dk_c = dk_c + dk_t
            dv_c = dv_c + dv_t
            # dK/dV accumulators travel WITH their chunk; after sp hops
            # they are home. K/V only need sp-1 hops (last compute used
            # the final position), so the last tick ships just the grads.
            if t < sp - 1:
                k_c, v_c, dk_c, dv_c = _ring_shift(
                    (k_c, v_c, dk_c, dv_c), axis_name, sp)
            else:
                dk_c, dv_c = _ring_shift((dk_c, dv_c), axis_name, sp)

    dq_ = fa._reshape_out(dq.astype(out_dtype), b, h)
    dk_ = fa._reshape_out(dk_c.astype(out_dtype), b, h)
    dv_ = fa._reshape_out(dv_c.astype(out_dtype), b, h)
    return dq_, dk_, dv_


_ring_mha.defvjp(_ring_fwd_res, _ring_bwd)


def raise_if_not_static(sp):
    if not isinstance(sp, int):
        raise TypeError(
            "ring_attention requires a static sp axis size (use it inside "
            "shard_map over a mesh axis)")


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale=None):
    """Blockwise ring attention for use INSIDE shard_map.

    q, k, v: [batch, seq_local, heads, head_dim] — the local sequence
    shard. Returns the local shard of the attention output. Differentiable
    (custom VJP runs the backward ring).
    """
    from ..distributed import context as dctx

    return _ring_mha(q, k, v, causal, scale, axis_name,
                     dctx.current_pipeline_auto_axes())


def sequence_parallel_attention(q, k, v, mesh: Mesh, causal: bool = True,
                                scale=None, axis_name: str = "sp"):
    """shard_map wrapper: q/k/v are GLOBAL [B, S, H, D] arrays (or traced
    values inside a pjit program); sequence dim is sharded over
    `axis_name`, everything else stays in GSPMD auto mode (so dp-sharded
    batch and tp-sharded heads compose).

    jax < 0.5 (no ``jax.shard_map``): the old experimental dialect
    cannot TRANSPOSE a partially-manual region (its ``auto=`` mode —
    the ROADMAP open item), so the wrapper goes ALL-manual there
    instead: manual over every mesh axis, with the batch dim explicitly
    mapped to 'dp' and the head dim to 'tp' when those axes exist and
    divide the dim (attention rows are independent per batch×head, so
    any even split is exact). Unmapped extra axes replicate. Same math,
    same ring — only the partitioning dialect differs. Routed through
    ``distributed/_compat.shard_map`` so the translation cannot drift
    per call site."""
    from ..distributed._compat import shard_map as _shard_map

    # when already inside another shard_map (e.g. the 'pp' pipeline,
    # distributed/pipeline.py), the context mesh is an AbstractMesh with
    # that axis Manual — the nested shard_map must be given THAT mesh.
    use_mesh = mesh
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is not None and axis_name in (am.axis_names or ()):
            use_mesh = am
    except AttributeError:
        pass

    modern = hasattr(jax, "shard_map")
    if modern:
        spec = P(None, axis_name, None, None)
        # inside this sp-manual region the other mesh axes stay
        # GSPMD-auto; pass them as the kernels' auto-context so the
        # chunk kernels nest a shard_map over them on the TPU target
        # (Mosaic cannot live in a partially-manual region) — threaded
        # through _ring_mha's static args so the transpose-time
        # backward sees it too
        remaining = tuple(a for a in mesh.axis_names if a != axis_name)
        auto_ctx = (mesh, remaining) if remaining else None
        manual = frozenset({axis_name})
    else:
        def _dim_axis(name, dim):
            ok = (name in mesh.axis_names and mesh.shape[name] > 1
                  and dim % mesh.shape[name] == 0)
            return name if ok else None

        b, _, h, _ = q.shape
        spec = P(_dim_axis("dp", b), axis_name, _dim_axis("tp", h), None)
        auto_ctx = None         # fully manual: no auto region to nest in
        manual = None           # _compat: None == manual over ALL axes

    mapped = _shard_map(
        lambda a, b_, c: _ring_mha(a, b_, c, causal, scale, axis_name,
                                   auto_ctx),
        mesh=use_mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False, axis_names=manual)
    return mapped(q, k, v)
