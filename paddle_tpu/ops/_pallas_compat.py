"""jax version compatibility for the Pallas TPU kernels — the ONE place
the pltpu compiler-params rename is absorbed (same rule as
distributed/_compat.py: a per-site copy of a version shim drifts).

jax < 0.5 names it ``TPUCompilerParams``; newer jax ``CompilerParams``.
The kwargs are identical.
"""
from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu


def _no_compiler_params(*_a, **_k):
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams on this jax version — update "
        "paddle_tpu/ops/_pallas_compat.py")


CompilerParams = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams",
                                 _no_compiler_params))
