"""Custom TPU kernels (Pallas) and fused ops.

TPU-native analogue of the reference's operators/fused/ — but only where XLA
doesn't already fuse well (SURVEY.md §7: attention, fused optimizer update).
"""
from . import flash_attention  # noqa: F401
from . import paged_attention  # noqa: F401
from . import ring_attention  # noqa: F401
