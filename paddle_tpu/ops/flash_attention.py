"""Flash attention — hand-tiled Pallas TPU kernel, fwd + bwd.

New capability vs the reference: the reference has no fused *training*
attention at all (SURVEY.md §5 "Long-context" — only the inference-side
multihead_matmul op, reference operators/fused/multihead_matmul_op.cu built by
framework/ir/multihead_matmul_fuse_pass.cc). Training attention there is an
unfused python composition over matmul/softmax kernels
(python/paddle/nn/layer/transformer.py). On TPU the attention kernel is the
MFU make-or-break (SURVEY.md §7 "Hard parts"), so it is first-class here:

  - forward: online-softmax (flash) tiling. Grid (batch·heads, q_blocks,
    k_blocks); the k dimension is the innermost ("arbitrary") axis so the
    running max / denominator / accumulator live in VMEM scratch across k
    steps. Logits never materialize in HBM: O(S) memory instead of O(S^2).
  - backward: recompute-based flash backward as two kernels — one accumulates
    dQ (grid over q blocks), one accumulates dK/dV (grid over k blocks) —
    using the saved logsumexp and the precomputed row dot delta = sum(dO·O).
  - causal masking: fully-masked tiles skip all compute (the MXU never sees
    them) and tiles below the diagonal skip mask evaluation. Dead-tile K/V
    DMA is elided by clamping the K-block index map to the diagonal
    (``lax.min(j, i)``): Pallas only issues a copy when a block index
    changes between grid steps, so once the k index saturates at the
    diagonal no further HBM traffic happens for that q row — causal
    attention reads half the K/V bytes of full attention.

All kernel math is f32 (MXU accumulates f32 even for bf16 inputs via
preferred_element_type); outputs are cast back to the input dtype.

The public entry keeps the paddle layout [batch, seq, heads, head_dim]
(reference python/paddle API convention) and composes with the eager tape via
jax.custom_vjp. On CPU (tests) the kernel runs in Pallas interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams as _CompilerParams

from ..tensor._helper import apply

_BLOCK_Q = 1024        # default tile edges (capped by seq len). Large tiles
_BLOCK_K = 1024        # amortize grid/DMA overhead and, at 1024, collapse
                       # seq<=1024 to ONE tile per (batch,head) — no
                       # running-softmax rescale passes (measured +5% MFU
                       # on GPT-350M vs 512 tiles; logits tile is 4 MiB
                       # f32, comfortably in VMEM). Equal q/k tiles under
                       # causal so the diagonal block covers its own row.
_SEQ_ALIGN = 128
_NEG_INF = -1e30

# The kernel's matmul semantics are part of the kernel, not of global
# config: under jax_default_matmul_precision="highest" (the test suite's
# golden-value setting) an unpinned dot_general would ask Mosaic for
# fp32-precision bf16 matmuls, which the bundled libtpu rejects ("Bad lhs
# type") — and 6-pass emulation is never what a flash kernel wants anyway.
_dot = functools.partial(jax.lax.dot_general,
                         precision=jax.lax.Precision.DEFAULT)
_LOG2E = 1.4426950408889634   # softmax runs in base 2: exp(x) = exp2(x·log2e)
_LN2 = 0.6931471805599453     # (exp2 is the TPU-native transcendental)


def _interpret() -> bool:
    from ..core.place import target_platform

    return target_platform() == "cpu"


def _causal_mask(iq, ik, block_q, block_k):
    qi = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    ki = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qi >= ki


def _tile_class(iq, ik, block_q, block_k):
    """(live, crosses_diagonal) for causal tile (iq, ik)."""
    q_lo, q_hi = iq * block_q, iq * block_q + block_q - 1
    k_lo, k_hi = ik * block_k, ik * block_k + block_k - 1
    live = k_lo <= q_hi
    diag = live & (k_hi > q_lo)
    return live, diag


def _pick_block(seq, cap):
    """Largest block edge <= cap that divides seq (128-aligned), else None."""
    b = min(cap, seq)
    while b >= _SEQ_ALIGN:
        if seq % b == 0:
            return b
        b //= 2
    return None


def supported(q_shape, attn_mask, dropout_p, kv_seq=None) -> bool:
    """True when the Pallas kernel handles this case; else jnp path."""
    if attn_mask is not None or dropout_p:
        return False
    if len(q_shape) != 4:
        return False
    if _pick_block(q_shape[1], _BLOCK_Q) is None:
        return False
    if kv_seq is not None and _pick_block(kv_seq, _BLOCK_K) is None:
        return False
    return q_shape[3] <= 256


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, *, scale, causal, block_q, block_k):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _tile(masked):
        q = q_ref[0]                                     # [bq, d]
        k = k_ref[0]                                     # [bk, d]
        v = v_ref[0]
        # base-2 logits: one fused scale, exp2 on the VPU (cheaper than exp)
        s = _dot(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * _LOG2E)
        if masked:
            mask = _causal_mask(iq, ik, block_q, block_k)
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:]                                # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_cur)
        p = jnp.exp2(s - m_cur)
        if masked:
            p = jnp.where(mask, p, 0.0)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + _dot(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_cur

    if causal:
        # tiles fully below the diagonal skip masking; tiles crossing it mask;
        # tiles fully above are dead (no compute, MXU never sees them)
        live, diag = _tile_class(iq, ik, block_q, block_k)
        pl.when(live & ~diag)(lambda: _tile(False))
        pl.when(diag)(lambda: _tile(True))
    else:
        _tile(False)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # m is base-2; export the natural-log lse (bwd/ring contract)
        lse_ref[0] = (m_ref[:] + jnp.log2(l_safe)) * _LN2   # [bq, 1]


def _fwd(q3, k3, v3, scale, causal, block_q, block_k):
    """q3/k3/v3: [BH, S, D] -> (o [BH, S, D], lse [BH, S, 1] f32)."""
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq, nk = sq // block_q, sk // block_k
    grid = (bh, nq, nk)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k)
    if causal:
        # dead tiles (j past the diagonal) re-reference the diagonal block;
        # an unchanged block index between grid steps elides the DMA
        kv_idx = lambda b, i, j: (b, jax.lax.min(j, i), 0)
    else:
        kv_idx = lambda b, i, j: (b, j, 0)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),       # running max
            pltpu.VMEM((block_q, 1), jnp.float32),       # running denom
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * sk * d // (2 if causal else 1),
            bytes_accessed=2 * (q3.size + k3.size + v3.size) * q3.dtype.itemsize,
            transcendentals=bh * sq * sk),
        interpret=_interpret(),
    )(q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _tile(masked):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                  # [bq, 1] natural
        delta = delta_ref[0]                              # [bq, 1]
        s = _dot(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * _LOG2E)
        if masked:
            s = jnp.where(_causal_mask(iq, ik, block_q, block_k), s,
                          _NEG_INF)
        p = jnp.exp2(s - lse * _LOG2E)                    # [bq, bk]
        dp = _dot(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += _dot(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        live, diag = _tile_class(iq, ik, block_q, block_k)
        pl.when(live & ~diag)(lambda: _tile(False))
        pl.when(diag)(lambda: _tile(True))
    else:
        _tile(False)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k):
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _tile(masked):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                  # [bq, 1] natural
        delta = delta_ref[0]                              # [bq, 1]
        s = _dot(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * _LOG2E)
        if masked:
            s = jnp.where(_causal_mask(iq, ik, block_q, block_k), s,
                          _NEG_INF)
        p = jnp.exp2(s - lse * _LOG2E)                    # [bq, bk]
        dv_acc[:] += _dot(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # p^T @ do
        dp = _dot(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                     # [bq, bk]
        dk_acc[:] += _dot(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # ds^T @ q

    if causal:
        live, diag = _tile_class(iq, ik, block_q, block_k)
        pl.when(live & ~diag)(lambda: _tile(False))
        pl.when(diag)(lambda: _tile(True))
    else:
        _tile(False)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_single_tile_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, dq_ref, dk_ref, dv_ref,
                            *, scale, causal):
    """Merged backward for the single-tile regime (whole sequence fits
    one q×k tile — the default at seq<=1024): s and p = exp2(s−lse) are
    computed ONCE and reused for dq, dk, and dv, where the two-kernel
    path recomputes them per kernel. Saves a full logits recompute per
    layer per step."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]                                      # [bq, 1] natural
    delta = delta_ref[0]                                  # [bq, 1]
    s = _dot(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (scale * _LOG2E)
    if causal:
        s = jnp.where(_causal_mask(0, 0, q.shape[0], k.shape[0]), s,
                      _NEG_INF)
    p = jnp.exp2(s - lse * _LOG2E)                        # [bq, bk]
    dv_ref[0] = _dot(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = _dot(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dsq = ds.astype(q.dtype)
    dq_ref[0] = _dot(
        dsq, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0] = _dot(
        dsq, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _bwd_single_tile(scale, causal, res, do3, delta, dtypes):
    q3, k3, v3, lse = res
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    dq_dtype, dk_dtype, dv_dtype = dtypes
    kern = functools.partial(_bwd_single_tile_kernel, scale=scale,
                             causal=causal)
    dq, dk, dv = pl.pallas_call(
        kern,
        grid=(bh,),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sq, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, sq, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), dq_dtype),
            jax.ShapeDtypeStruct((bh, sk, d), dk_dtype),
            jax.ShapeDtypeStruct((bh, sk, d), dv_dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


def _bwd(scale, causal, block_q, block_k, res, do3, delta=None,
         out_dtype=None):
    """delta/out_dtype are overridable for the ring-attention caller
    (ops/ring_attention.py): there delta is a property of the GLOBAL
    output row (computed once outside the ring) and per-chunk partials
    must come back f32 so the ring accumulation doesn't round."""
    q3, k3, v3, o3, lse = res
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq, nk = sq // block_q, sk // block_k
    if delta is None:
        delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                        axis=-1, keepdims=True)           # [BH, S, 1]
    dq_dtype = out_dtype or q3.dtype
    dk_dtype = out_dtype or k3.dtype
    dv_dtype = out_dtype or v3.dtype

    if nq == 1 and nk == 1:
        return _bwd_single_tile(scale, causal, (q3, k3, v3, lse), do3,
                                delta, (dq_dtype, dk_dtype, dv_dtype))

    if causal:
        # same dead-tile DMA elision as the forward (see module docstring)
        kv_idx = lambda b, i, j: (b, jax.lax.min(j, i), 0)
        q_row_idx = lambda b, j, i: (b, jax.lax.max(i, j), 0)
    else:
        kv_idx = lambda b, i, j: (b, j, 0)
        q_row_idx = lambda b, j, i: (b, i, 0)

    dq_kern = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                block_q=block_q, block_k=block_k)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), dq_dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)[0]

    dkv_kern = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                                 block_q=block_q, block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_row_idx),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_row_idx),
            pl.BlockSpec((1, block_q, 1), q_row_idx),
            pl.BlockSpec((1, block_q, 1), q_row_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), dk_dtype),
            jax.ShapeDtypeStruct((bh, sk, d), dv_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper (pure jax level, [B, S, H, D] layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_mha(q, k, v, causal, scale):
    o, _ = _flash_fwd_res(q, k, v, causal, scale)
    return o


def _reshape_in(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _reshape_out(x3, b, h):
    bh, s, d = x3.shape
    return jnp.swapaxes(x3.reshape(b, h, s, d), 1, 2)


def _flash_fwd_res(q, k, v, causal, scale):
    b, sq, h, d = q.shape
    s_val = scale if scale is not None else 1.0 / (d ** 0.5)
    sk = k.shape[1]
    bq = _pick_block(sq, _BLOCK_Q)
    bk = _pick_block(sk, _BLOCK_K)
    if bq is None or bk is None:
        raise ValueError(
            f"flash_attention needs 128-aligned seq lens, got q={sq} kv={sk}")
    if causal:
        if sq != sk:
            raise ValueError("causal flash_attention requires seq_q == seq_kv")
        bq = bk = min(bq, bk)
    q3, k3, v3 = _reshape_in(q), _reshape_in(k), _reshape_in(v)
    o3, lse = _fwd(q3, k3, v3, s_val, causal, bq, bk)
    return _reshape_out(o3, b, h), (q3, k3, v3, o3, lse, b, h, s_val, bq, bk)


def _flash_mha_bwd(causal, scale, res, do):
    q3, k3, v3, o3, lse, b, h, s_val, bq, bk = res
    do3 = _reshape_in(do)
    dq3, dk3, dv3 = _bwd(s_val, causal, bq, bk, (q3, k3, v3, o3, lse), do3)
    return (_reshape_out(dq3, b, h), _reshape_out(dk3, b, h),
            _reshape_out(dv3, b, h))


_flash_mha.defvjp(_flash_fwd_res, _flash_mha_bwd)


def _maybe_nested_shard(q_shape, causal, scale):
    """Inside the pipeline's manual-'pp' region the remaining mesh axes
    are GSPMD-auto, and XLA refuses to auto-partition a Mosaic kernel in
    a partially-manual region. Returns a callable that nests a shard_map
    over those axes (dp shards batch, tp shards heads — the framework's
    axis convention) so every mesh axis is manual around the pallas call,
    or None when not applicable (full-auto region, CPU interpret, or
    non-divisible shapes → caller falls back)."""
    from ..distributed import context as dctx

    pa = dctx.current_pipeline_auto_axes()
    if pa is None or _interpret():
        return None
    mesh, axes = pa
    from jax.sharding import PartitionSpec as P

    b, s, h, d = q_shape
    dp = mesh.shape.get("dp", 1) if "dp" in axes else 1
    tp = mesh.shape.get("tp", 1) if "tp" in axes else 1
    if b % max(dp, 1) or h % max(tp, 1):
        return None
    spec = P("dp" if dp > 1 else None, None, "tp" if tp > 1 else None,
             None)

    def call(q, k, v):
        fn = dctx.nested_kernel_shard(
            lambda q_, k_, v_: _flash_mha(q_, k_, v_, causal, scale),
            in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)

    return call


def flash_attention(query, key, value, causal=False, scale=None, name=None):
    """q,k,v: [batch, seq, heads, head_dim] -> [batch, seq, heads, head_dim].

    Tape-level entry (Tensor in/out). ``_flash_mha`` is the pure-jax kernel
    entry used by jitted functional paths (distributed/hybrid_gpt.py).
    """
    def f(q, k, v):
        nested = _maybe_nested_shard(q.shape, causal, scale)
        if nested is not None:
            return nested(q, k, v)
        if _pipeline_partial_manual():
            # partially-manual region but shapes not shardable: the
            # Mosaic kernel would be rejected — use the auto-partitionable
            # jnp reference instead
            return mha_reference(q, k, v, causal, scale)
        return _flash_mha(q, k, v, causal, scale)

    return apply(f, query, key, value, name="flash_attention")


def _pipeline_partial_manual() -> bool:
    from ..distributed import context as dctx

    return dctx.in_partial_manual_region()


def mha_reference(q, k, v, causal=False, scale=None):
    """Unfused reference (tests compare the kernel against this)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = (jnp.einsum("bhsd,bhtd->bhst", qt, kt) * s).astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


# back-compat alias (pre-kernel rounds exposed the reference as the impl)
_mha_reference = mha_reference
