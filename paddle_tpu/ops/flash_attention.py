"""Flash attention for TPU.

New capability vs the reference (SURVEY.md §5: the reference has no fused
training attention). Round-1 ships the blockwise-softmax jnp formulation
(XLA fuses it into a flash-style loop under jit); the hand-tiled Pallas
kernel lands behind the same API.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..tensor._helper import apply

_PALLAS_MIN_SEQ = 1 << 30  # Pallas kernel gate; lowered when kernel lands.


def supported(q_shape, attn_mask, dropout_p) -> bool:
    return False  # jnp path used until the Pallas kernel is enabled


def flash_attention(query, key, value, causal=False, scale=None, name=None):
    """q,k,v: [batch, seq, heads, head_dim] -> [batch, seq, heads, head_dim]."""
    def f(q, k, v):
        return _mha_reference(q, k, v, causal=causal, scale=scale)

    return apply(f, query, key, value, name="flash_attention")


@functools.partial(jax.jit, static_argnames=("causal", "scale"))
def _mha_reference(q, k, v, causal=False, scale=None):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = (jnp.einsum("bhsd,bhtd->bhst", qt, kt) * s).astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)
