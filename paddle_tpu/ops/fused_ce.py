"""Fused lm-head + softmax cross-entropy (chunked, logits never in HBM).

The reference fuses softmax+CE in one CUDA kernel
(reference paddle/fluid/operators/softmax_with_cross_entropy_op.cu) but
still materializes the full [tokens, vocab] logits produced by the
preceding matmul. On TPU the HBM traffic of those logits dominates the
loss computation for LM-scale vocabularies (batch 8 × seq 1024 × vocab
32768 in f32 is >1 GB per direction), so here the *projection itself* is
fused into the loss:

  - ``lax.scan`` over sequence chunks; each chunk computes its logits
    tile ``x_chunk @ W^T`` (f32 MXU accumulation), reduces it to
    logsumexp + the gold-label logit, and discards it — peak logits
    footprint is one chunk, not the full sequence.
  - the scan body is ``jax.checkpoint``-ed: backward rematerializes each
    chunk's logits instead of storing them, trading one extra matmul
    pass for O(seq/chunk) memory.
  - grads flow to both ``x`` and the (possibly vocab-sharded) weight
    through the scan transpose; under GSPMD a tp-sharded vocab axis
    turns the logsumexp into a psum automatically.

Used by the hybrid trainer's loss head (distributed/hybrid_gpt.py) and
exposed as ``paddle_tpu.nn.functional.fused_linear_cross_entropy``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor._helper import apply

IGNORE = -100


def _fused_ce(x, w, labels, ignore_index, chunk, w_is_vh, bias=None):
    """x: [B, S, H]; w: [V, H] (embedding layout) or [H, V]; labels [B, S];
    bias: optional [V] added to the logits (e.g. BERT's tied MLM decoder).

    Returns mean CE over non-ignored positions, f32 scalar.
    """
    b, s, h = x.shape
    if chunk is None or chunk >= s:
        nc, cs = 1, s
    else:
        cs = chunk
        while s % cs:            # shrink to a divisor (seq is 128-aligned)
            cs //= 2
        nc = s // cs
    xs = x.reshape(b, nc, cs, h).transpose(1, 0, 2, 3)       # [nc, B, cs, H]
    ls = labels.reshape(b, nc, cs).transpose(1, 0, 2)        # [nc, B, cs]
    v = w.shape[0] if w_is_vh else w.shape[1]

    def body(carry, inp):
        xc, lc = inp                                          # [B,cs,H] [B,cs]
        # contract H: w is [V, H] when transpose_w else [H, V]
        wdim = 1 if w_is_vh else 0
        logits = jax.lax.dot_general(
            xc, w, (((2,), (wdim,)), ((), ())),
            preferred_element_type=jnp.float32)               # [B, cs, V]
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        m = jnp.max(logits, axis=-1, keepdims=True)
        lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1)) + m[..., 0]
        mask = lc != ignore_index
        safe = jnp.clip(lc, 0, v - 1)
        # gold logit via one-hot contraction, not take_along_axis: XLA
        # fuses it to a select+reduce (no [B,cs,V] materialization), and —
        # load-bearing — GSPMD partitions it cleanly when V is tp-sharded
        # and the batch dp-sharded inside a manual-pp shard_map region,
        # where the equivalent gather crashes the SPMD partitioner
        # (spmd_partitioner_util.cc partition-group check).
        onehot = jax.nn.one_hot(safe, v, dtype=logits.dtype)
        gold = jnp.einsum("bsv,bsv->bs", logits, onehot)
        loss = jnp.where(mask, lse - gold, 0.0)
        acc, n = carry
        return (acc + jnp.sum(loss),
                n + jnp.sum(mask.astype(jnp.int32))), None

    (total, n), _ = jax.lax.scan(
        jax.checkpoint(body),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (xs, ls))
    return total / jnp.maximum(n, 1).astype(jnp.float32)


def fused_linear_cross_entropy_fn(x, w, labels, ignore_index=IGNORE,
                                  chunk=256, transpose_w=False, bias=None):
    """Pure-jax entry (used inside jitted trainers).

    ``transpose_w=False``: w is [V, H] (tied-embedding layout, logits =
    x @ w.T). ``transpose_w=True``: w is [H, V] (Linear layout).
    """
    return _fused_ce(x, w, labels, ignore_index, chunk, not transpose_w,
                     bias=bias)


def shifted_labels(tokens, ignore_index=IGNORE):
    """Next-token labels: tokens shifted left, last position ignored."""
    return jnp.concatenate(
        [tokens[:, 1:],
         jnp.full((tokens.shape[0], 1), ignore_index, tokens.dtype)], axis=1)


def fused_linear_cross_entropy(x, weight, labels, ignore_index=IGNORE,
                               chunk=256, transpose_w=False, bias=None,
                               next_token=False, name=None):
    """Tape-level entry (Tensor in/out). ``next_token=True`` shifts the
    labels left by one (LM objective) before the loss."""
    def f(xv, wv, lv, *rest):
        if next_token:
            lv = shifted_labels(lv, ignore_index)
        return fused_linear_cross_entropy_fn(
            xv, wv, lv, ignore_index=ignore_index, chunk=chunk,
            transpose_w=transpose_w, bias=rest[0] if rest else None)

    args = (x, weight, labels) + ((bias,) if bias is not None else ())
    return apply(f, *args, name="fused_linear_cross_entropy")
