"""Fused int8 matmul Pallas kernel: quantize → int8×int8→int32 MXU dot
→ dequant/bias/activation epilogue in ONE kernel.

Why (VERDICT r4 next #2): the unfused int8 serving path
(quantization.Int8Linear) lowers to XLA as three stages —

    f32 x ── round/clip ──▶ int8 xq  ──▶ MXU dot ──▶ int32 acc ──▶
    acc·scale + bias (f32 epilogue pass)

— and the int32 accumulator plus the quantize pass round-trip HBM.
At the serving bench's shapes ([4096, 4096]×[4096, 16384]) that is
~0.5 GB of avoidable traffic per layer, and the measured int8 dots ran
at ~43% of the v5e's int8 peak vs the bf16 artifact's ~61% (bench.py
predictor roofline note). This kernel keeps the quantize on the VPU
overlapped with the MXU dot, accumulates in VMEM, and applies the
dequant epilogue (per-channel scale, bias, optional ReLU, optional
re-quantize to int8 for a following int8 layer) before anything
touches HBM: per-layer HBM traffic becomes one read of x + one read
of wq + one write of the (possibly int8) output.

Reference analogue: the slim int8 deploy path hands quantized programs
to fused cuDNN/TensorRT int8 kernels inside AnalysisPredictor
(reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py, paddle/fluid/inference/api/analysis_predictor.cc);
this is the TPU-native equivalent of those fused kernels.

Math matches Int8Linear's unfused expression to f32 rounding (same
round-half-even, same clip bounds), so QAT-eval parity carries over.
On CPU (tests) the kernel runs in Pallas interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams as _CompilerParams


def _interpret() -> bool:
    from ..core.place import target_platform

    return target_platform() == "cpu"


def _kernel(x_ref, wq_ref, qs_ref, sc_ref, bi_ref, out_ref, acc_ref, *,
            nk: int, amax: float, relu: bool, quant_out: bool,
            x_quantized: bool):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if x_quantized:
        xq = x_ref[:]
    else:
        # quantize on the VPU, overlapped with the MXU dot
        xq = jnp.clip(jnp.round(x_ref[:].astype(jnp.float32)
                                * qs_ref[0, 0]),
                      -amax, amax).astype(jnp.int8)
    acc_ref[:] += jax.lax.dot_general(
        xq, wq_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == nk - 1)
    def _epilogue():
        y = acc_ref[:].astype(jnp.float32) * sc_ref[:] + bi_ref[:]
        if relu:
            y = jnp.maximum(y, 0.0)
        if quant_out:
            out_ref[:] = jnp.clip(jnp.round(y), -amax, amax) \
                .astype(jnp.int8)
        else:
            out_ref[:] = y.astype(out_ref.dtype)


def _pad_to(a, axis, mult):
    n = a.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


@functools.partial(
    jax.jit, static_argnames=("relu", "quant_out", "out_dtype", "amax",
                              "block_m", "block_n", "block_k"))
def int8_matmul(x, wq, scale, bias=None, qscale=None, *,
                relu: bool = False, quant_out: bool = False,
                out_dtype=jnp.float32, amax: float = 127.0,
                block_m: int = 512, block_n: int = 512,
                block_k: int = 512):
    """y = dequant(quantize(x) @ wq) [+ bias] [relu] [requantize].

    x:      [M, K] float (quantized in-kernel with ``qscale``) or int8
            (pre-quantized; ``qscale`` ignored).
    wq:     [K, N] int8.
    scale:  [N] f32 — combined dequant scale applied to the int32
            accumulator (caller folds (s_act/amax)·(s_w/wmax) and, for
            ``quant_out``, the NEXT layer's amax/s_act into it).
    bias:   optional [N] f32, added post-scale (pre-ReLU). For
            ``quant_out`` the caller folds the next quant scale in.
    quant_out: emit int8 (clip(round(y))) for a following int8 layer —
            the f32 intermediate never exists in HBM.
    """
    m, kdim = x.shape
    n = wq.shape[1]
    bm, bn, bk = (min(block_m, m), min(block_n, n), min(block_k, kdim))
    xp = _pad_to(_pad_to(x, 0, bm), 1, bk)
    wp = _pad_to(_pad_to(wq, 0, bk), 1, bn)
    sp = _pad_to(scale.reshape(1, -1).astype(jnp.float32), 1, bn)
    bp = _pad_to(
        (bias if bias is not None
         else jnp.zeros((n,), jnp.float32)).reshape(1, -1)
        .astype(jnp.float32), 1, bn)
    qs = jnp.asarray(qscale if qscale is not None else 1.0,
                     jnp.float32).reshape(1, 1)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    nk = kp // bk
    grid = (mp // bm, np_ // bn, nk)
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, amax=float(amax), relu=relu,
                          quant_out=quant_out,
                          x_quantized=(x.dtype == jnp.int8)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, 1), lambda i, j, k: (0, 0)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct(
            (mp, np_), jnp.int8 if quant_out else out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(xp, wp, qs, sp, bp)
    return out[:m, :n]


def int8_linear_fused(x, wq, w_scale, act_scale, bias=None, *,
                      wmax: float = 127.0, amax: float = 127.0,
                      relu: bool = False,
                      next_act_scale: Optional[jax.Array] = None,
                      out_dtype=jnp.float32):
    """Int8Linear's math through the fused kernel.

    Folds the per-channel dequant (and, when ``next_act_scale`` is
    given, the next layer's activation quantization) into the kernel
    epilogue:

        y   = (xq @ wq) · (s_a/amax)·(s_w/wmax) + b          (f32)
        yq  = clip(round(y · amax/s_a'))                      (int8)

    x may be f32/bf16 (quantized in-kernel) or int8 (output of a
    previous ``quant_out`` layer).
    """
    sa = jnp.maximum(jnp.asarray(act_scale, jnp.float32), 1e-8)
    ws = jnp.maximum(jnp.asarray(w_scale, jnp.float32), 1e-8)
    scale = (sa / amax) * (ws / wmax)
    b = None if bias is None else jnp.asarray(bias, jnp.float32)
    quant_out = next_act_scale is not None
    if quant_out:
        nq = amax / jnp.maximum(jnp.asarray(next_act_scale, jnp.float32),
                                1e-8)
        scale = scale * nq
        if b is not None:
            b = b * nq
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    y = int8_matmul(x2, wq, scale, b, qscale=amax / sa, relu=relu,
                    quant_out=quant_out, out_dtype=out_dtype, amax=amax)
    return y.reshape(lead + (wq.shape[1],))
