"""paddle.set_printoptions (reference: python/paddle/tensor/to_string.py).

Tensor __repr__ prints via numpy, so the implementation simply bridges
to numpy's printoptions with the reference's parameter names.
"""
import numpy as np


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    np.set_printoptions(**kw)
