"""Eager Tensor and Parameter.

TPU-native analogue of the reference's imperative VarBase/VariableWrapper
(reference: paddle/fluid/imperative/layer.h, variable_wrapper.h) and the
framework Tensor (framework/tensor.h:305).

A Tensor wraps a ``jax.Array`` (device memory managed by the XLA runtime —
this subsumes the reference's AllocatorFacade, memory/allocation/) plus
autograd metadata used by the tape engine in ``paddle_tpu.autograd.tape``.
Under ``jax.jit`` tracing, ``_value`` may hold a tracer; all methods that
stay in jax-land keep working, so the same Layer code runs eagerly and
compiled (the reference needed a separate dygraph-to-static translator for
this; on TPU it is free).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.place import CPUPlace, Place, TPUPlace, expected_place


class Tensor:
    # Make numpy defer binary-op dispatch to us.
    __array_priority__ = 100

    def __init__(self, value, dtype=None, place: Optional[Place] = None,
                 stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        if isinstance(value, jax.ShapeDtypeStruct):
            # abstract (LazyGuard) tensor: metadata only, no buffer —
            # the 13B-scale AOT planning path (framework/lazy.py)
            self._value = value
            self.stop_gradient = stop_gradient
            self.grad = None
            self._node = None
            self._out_idx = 0
            self.name = name or ""
            self.persistable = False
            self._place = place
            return
        if not isinstance(value, (jax.Array,)) or dtype is not None:
            d = dtype_mod.convert_dtype(dtype) if dtype is not None else None
            if d is None and isinstance(value, (float,)):
                d = dtype_mod.get_default_dtype()
            if d is None and isinstance(value, (list, tuple)):
                probe = np.asarray(value)
                if probe.dtype == np.float64:
                    d = dtype_mod.get_default_dtype()
            if d is None and isinstance(value, np.ndarray) and \
                    value.dtype == np.float64:
                # Match paddle: python/numpy float data defaults to fp32.
                d = dtype_mod.get_default_dtype()
            value = jnp.asarray(value, dtype=d)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._node = None          # producing tape Node
        self._out_idx = 0
        self.name = name or ""
        self.persistable = False
        self._place = place

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape, dtype=np.int64))

    @property
    def place(self):
        if self._place is not None:
            return self._place
        try:
            dev = list(self._value.devices())[0]
            return CPUPlace() if dev.platform == "cpu" else TPUPlace(dev.id)
        except Exception:
            return expected_place()

    @property
    def is_leaf(self):
        return self._node is None

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __index__(self):
        return int(np.asarray(self._value))

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        body = repr(np.asarray(self._value)) if not self._is_traced() \
            else f"<traced {self._value.aval}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient},\n{body})")

    def _is_traced(self):
        return not isinstance(self._value, jax.Array) or \
            isinstance(self._value, jax.core.Tracer)

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from ..autograd import tape

        tape.backward([self], None if grad_tensor is None else [grad_tensor],
                      retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..autograd import tape

        return tape.apply(lambda x: x + 0, self, name="clone")

    def register_hook(self, hook):
        raise NotImplementedError(
            "Tensor.register_hook: planned for the eager tape (round 2).")

    # -- conversion / movement --------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from ..autograd import tape

        d = dtype_mod.convert_dtype(dtype)
        return tape.apply(lambda x: x.astype(d), self, name="cast")

    cast = astype

    def to(self, *args, **kwargs):
        _DEVICE_NAMES = ("cpu", "gpu", "tpu", "xpu", "cuda")
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, Place):
                t = t.cpu() if isinstance(a, CPUPlace) else \
                    t.cuda(a.get_device_id())
            elif isinstance(a, str) and \
                    a.split(":")[0].lower() in _DEVICE_NAMES:
                name = a.lower()
                if name.startswith("cpu"):
                    t = t.cpu()
                else:
                    idx = int(name.split(":")[1]) if ":" in name else 0
                    t = t.cuda(idx)
            else:
                t = t.astype(a)  # dtype string / dtype object
        return t

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def cuda(self, device_id: int = 0, blocking: bool = True) -> "Tensor":
        return Tensor(jax.device_put(
            self._value, TPUPlace(device_id).jax_device()),
            stop_gradient=self.stop_gradient)

    tpu = cuda

    def pin_memory(self):
        return self.cpu()

    # -- in-place mutation --------------------------------------------------
    # Full-overwrite mutations (set_value/zero_/fill_) follow reference
    # VarBase.set_value semantics: the tensor becomes a fresh leaf — any
    # previous producer node is detached so backward cannot mix the
    # overwritten value with the old op's vjp.
    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        self._value = v.astype(self._value.dtype) if hasattr(v, "astype") else v
        self._node = None
        self._out_idx = 0
        return self

    def copy_(self, other, blocking: bool = True):
        return self.set_value(other)

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        self._node = None
        self._out_idx = 0
        return self

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        self._node = None
        self._out_idx = 0
        return self

    # Arithmetic inplace ops are differentiable in the reference
    # (op_function_generator.cc inplace variants); route through the tape
    # with rebinding so gradients stay correct.
    def scale_(self, scale):
        from ..tensor._helper import inplace_apply

        return inplace_apply(lambda v: v * scale, self, name="scale_")

    def add_(self, other):
        from ..tensor._helper import inplace_apply

        return inplace_apply(lambda v, o: v + o, self, other, name="add_")

    def subtract_(self, other):
        from ..tensor._helper import inplace_apply

        return inplace_apply(lambda v, o: v - o, self, other, name="subtract_")

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        from ..autograd import tape

        if isinstance(idx, Tensor):
            idx = idx._value
        elif isinstance(idx, tuple):
            idx = tuple(i._value if isinstance(i, Tensor) else i for i in idx)
        return tape.apply(lambda x: x[idx], self, name="getitem")

    def __setitem__(self, idx, value):
        if isinstance(idx, Tensor):
            idx = idx._value
        v = value._value if isinstance(value, Tensor) else value
        self._value = self._value.at[idx].set(v)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # Arithmetic operators are attached by paddle_tpu.tensor._install_methods
    # (single table shared with the functional op library).


class Parameter(Tensor):
    """Trainable tensor (reference: fluid/framework.py Parameter,
    imperative VarBase with persistable=True)."""

    def __init__(self, value, dtype=None, name: Optional[str] = None,
                 trainable: bool = True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor equivalent."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
