"""Abstract (lazy) parameter initialization — ``paddle.LazyGuard``.

Reference parity: Paddle's LazyGuard (python/paddle lazy init for
billion-parameter models whose eager init would not fit host RAM).
TPU-native translation: under the guard, ``build_parameter`` creates
Parameters whose ``_value`` is a ``jax.ShapeDtypeStruct`` — pure
metadata, zero bytes materialized. A lazily-built model can be:

  * AOT-lowered/compiled through the hybrid trainer
    (``HybridPipelineTrainer(..., abstract)`` detects the struct values
    and plans shardings + optimizer state abstractly) — this is how the
    GPT-3 13B memory plan (benchmarks/plan_13b.py, BENCH_13B_PLAN.json)
    compiles a 52 GB-state model on a laptop-sized host;
  * materialized later with ``materialize(model)`` (per-tensor init on
    demand, e.g. after sharding decisions are known).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

_state = threading.local()


def in_lazy_mode() -> bool:
    return getattr(_state, "lazy", False)


class LazyGuard:
    """Context manager: parameters created inside are abstract."""

    def __enter__(self):
        self._prev = getattr(_state, "lazy", False)
        _state.lazy = True
        return self

    def __exit__(self, *exc):
        _state.lazy = self._prev
        return False


def is_abstract(t) -> bool:
    """True if a Tensor (or raw value) is a LazyGuard metadata-only
    placeholder."""
    v = getattr(t, "_value", t)
    return isinstance(v, jax.ShapeDtypeStruct)


def materialize(layer, key=None):
    """Initialize every abstract parameter of ``layer`` for real, using
    each Parameter's recorded initializer (stashed by build_parameter)."""
    for _, p in layer.named_parameters():
        if p is not None and is_abstract(p):
            init = getattr(p, "_lazy_initializer", None)
            spec = p._value
            if init is None:
                p._value = jnp.zeros(spec.shape, spec.dtype)
            else:
                p._value = jnp.asarray(
                    init(list(spec.shape), spec.dtype), spec.dtype)
    return layer
