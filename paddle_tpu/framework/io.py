"""Checkpoint save/load (reference: python/paddle/framework/io.py:202,292 —
pickled per-tensor numpy state dicts; C++ save/load ops operators/save_op.cc).

Format-compatible idea: a dict of numpy arrays pickled to disk. Sharded /
async multi-host checkpointing for the distributed path lives in
paddle_tpu.distributed.checkpoint — per-mesh-shard files streamed through
the native async writer (native/src/file_writer.cc), commit-marker
crash consistency, resume-exact restore.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    try:
        import jax

        if isinstance(obj, jax.Array):
            return np.asarray(obj)
    except Exception:
        pass
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """paddle.save equivalent."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, **configs) -> Any:
    """paddle.load equivalent. Returns numpy-backed state (set_state_dict
    accepts numpy directly)."""
    with open(path, "rb") as f:
        return pickle.load(f)
