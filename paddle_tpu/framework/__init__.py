"""Framework core: Tensor/Parameter plus program-plan utilities
(reference: paddle/fluid/framework/)."""
from .param_attr import ParamAttr  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
