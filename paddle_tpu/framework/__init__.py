"""Framework core: Tensor/Parameter plus program-plan utilities
(reference: paddle/fluid/framework/)."""
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
