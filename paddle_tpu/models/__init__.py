"""Flagship model families (GPT for the hybrid-parallel north star,
BERT for the DP+AMP config)."""
from .gpt import GPT, GPTBlock, GPTConfig, gpt_tiny  # noqa: F401
