"""Flagship model families (GPT for the hybrid-parallel north star,
BERT for the DP+AMP config)."""
from .bert import (Bert, BertBlock, BertConfig, BertForPretraining,  # noqa: F401
                   bert_tiny)
from .ernie import (ErnieConfig, ErnieForPretraining,  # noqa: F401
                    ernie_tiny)
from .gpt import (GPT, GPTBlock, GPTConfig, GPTForGeneration,  # noqa: F401
                  gpt_cached_apply, gpt_tiny)
