"""GPT model family — the flagship for the hybrid-parallel north star
(BASELINE.md: GPT-3 1.3B/13B, TP×PP×sharding, ≥45% MFU target).

The reference has no GPT in-tree (its GPT configs ran via fleet meta
optimizers over user model code); here the model is first-class and
TPU-first:
  - attention through F.scaled_dot_product_attention (flash path),
  - q/kv/mlp projections as tensor-parallel layers carrying PartitionSpecs
    (distributed/parallel_layers.py) that the strategy compiler turns into
    GSPMD shardings,
  - identical block structure per layer so the compiled path can stack
    block params into [L, ...] arrays and lax.scan over layers (and shard
    the stage axis for pipeline parallelism).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..distributed import context as _dctx
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import arange


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_hidden_size: int = 0          # default 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # MoE (beyond-reference capability, distributed/moe.py): >0 replaces
    # every block's FFN with a num-experts MoE sharded over 'ep'
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if not self.ffn_hidden_size:
            self.ffn_hidden_size = 4 * self.hidden_size

    # presets from the reference north-star table (BASELINE.md)
    @staticmethod
    def gpt3_125m():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt3_350m():
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def gpt3_1_3b():
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_seq_len=2048)

    @staticmethod
    def gpt3_2_7b():
        return GPTConfig(hidden_size=2560, num_layers=32, num_heads=32,
                         max_seq_len=2048)

    @staticmethod
    def gpt3_6_7b():
        return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                         max_seq_len=2048)

    @staticmethod
    def gpt3_13b():
        return GPTConfig(hidden_size=5120, num_layers=40, num_heads=40,
                         max_seq_len=2048)

    def num_params(self) -> int:
        h, L, v = self.hidden_size, self.num_layers, self.vocab_size
        e = max(self.moe_num_experts, 1)     # E expert FFNs + router
        ffn = 2 * h * self.ffn_hidden_size * e \
            + (e - 1) * (self.ffn_hidden_size + h) \
            + (h * e if self.moe_num_experts else 0)
        per_block = 4 * h * h + ffn + 13 * h
        return v * h + self.max_seq_len * h + L * per_block + 2 * h

    def flops_per_token(self, seq_len=None) -> float:
        """Training FLOPs/token ≈ 6N + 12·L·h·s (attention term)."""
        s = seq_len or self.max_seq_len
        return 6.0 * self.num_params() + 12.0 * self.num_layers * \
            self.hidden_size * s


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range /
                            math.sqrt(2 * c.num_layers))
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, weight_attr=init,
            gather_output=False)
        self.out_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, weight_attr=out_init)
        self.dropout = c.dropout
        # qkv weight columns interleave q|k|v: shard on out dim stays valid
        self.qkv_proj.param_shardings = {"weight": P(None, "tp"),
                                         "bias": P("tp")}

    def forward(self, x):
        b, s, h = x.shape[0], x.shape[1], x.shape[2]
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)
        sp = _dctx.current_sequence_parallel()
        dropout_active = bool(self.dropout) and self.training
        if sp is not None:
            # sequence-parallel: ring attention over the 'sp' mesh axis
            # (ops/ring_attention.py) — seq dim stays sharded end to end.
            # Attention-prob dropout is not expressible in the ring (probs
            # never materialize): under sp it must be off. Inside the
            # manual region there is NO correct fallback (plain attention
            # would be block-diagonal over the local shard), so raise.
            from ..ops.ring_attention import (_ring_mha,
                                              sequence_parallel_attention)
            from ..tensor._helper import apply

            mesh, axis, manual = sp
            if dropout_active:
                raise NotImplementedError(
                    "attention-probability dropout is not supported under "
                    "sequence parallelism (ring attention); set "
                    "GPTConfig.dropout=0 or sp_degree=1")
            if manual:
                # already inside a shard_map manual over `axis`; capture
                # the remaining-auto-axes scope NOW — the custom_vjp
                # backward traces at transpose time, after the scope exits
                auto_ctx = _dctx.current_pipeline_auto_axes()
                fn = lambda q_, k_, v_: _ring_mha(q_, k_, v_, True, None,
                                                  axis, auto_ctx)
            else:
                fn = lambda q_, k_, v_: sequence_parallel_attention(
                    q_, k_, v_, mesh, causal=True, axis_name=axis)
            out = apply(fn, q, k, v, name="ring_attention")
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range /
                            math.sqrt(2 * c.num_layers))
        self.fc_in = ColumnParallelLinear(c.hidden_size, c.ffn_hidden_size,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(c.ffn_hidden_size, c.hidden_size,
                                        weight_attr=out_init)
        self.dropout = c.dropout

    def forward(self, x):
        x = F.gelu(self.fc_in(x), approximate=True)
        x = self.fc_out(x)
        return F.dropout(x, self.dropout, training=self.training)


class GPTBlock(nn.Layer):
    """Pre-norm transformer block; identical structure per layer (stackable)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        if config.moe_num_experts > 0:
            from ..distributed.moe import MoEMLP

            self.mlp = MoEMLP(config.hidden_size, config.ffn_hidden_size,
                              config.moe_num_experts,
                              top_k=config.moe_top_k,
                              capacity_factor=config.moe_capacity_factor,
                              initializer_range=config.initializer_range)
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size,
            weight_attr=I.Normal(0.0, c.initializer_range))
        self.wpe = nn.Embedding(
            c.max_seq_len, c.hidden_size,
            weight_attr=I.Normal(0.0, c.initializer_range))
        self.dropout = c.dropout

    def forward(self, tokens):
        s = tokens.shape[1]
        pos = arange(0, s, dtype="int64").unsqueeze(0)
        x = self.wte(tokens) + self.wpe(pos)
        return F.dropout(x, self.dropout, training=self.training)


class GPT(nn.Layer):
    """Decoder-only GPT. ``forward`` returns logits; ``loss`` computes the
    shifted next-token cross entropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                weight_attr=I.Normal(0.0, config.initializer_range),
                gather_output=True)

    def forward(self, tokens):
        x = self.embeddings(tokens)
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        if self.config.tie_word_embeddings:
            from ..tensor import matmul

            return matmul(x, self.embeddings.wte.weight, transpose_y=True)
        return self.lm_head(x)

    # --- pipeline protocol (distributed/hybrid.py) -----------------------
    def pipeline_stem(self, tokens):
        return self.embeddings(tokens)

    def pipeline_blocks(self):
        return self.blocks

    def pipeline_head(self, x, tokens, labels=None):
        """Final norm + fused lm-head/CE (ops/fused_ce.py): the [B,S,V]
        logits never materialize in HBM. ``labels`` (eager .loss path):
        explicit targets instead of the shifted-token LM objective."""
        from ..ops.fused_ce import fused_linear_cross_entropy

        x = self.ln_f(x)
        # chunking over seq would fight an sp sharding; sp>1 runs one chunk
        chunk = None if _dctx.current_sequence_parallel() else 256
        lbl, next_token = (tokens, True) if labels is None \
            else (labels, False)
        if self.config.tie_word_embeddings:
            return fused_linear_cross_entropy(
                x, self.embeddings.wte.weight, lbl, chunk=chunk,
                next_token=next_token)
        return fused_linear_cross_entropy(
            x, self.lm_head.weight, lbl, chunk=chunk, transpose_w=True,
            next_token=next_token)

    # --- decoding (ops/decoding.py loops over the KV-cached forward) -----
    def generate(self, input_ids, max_new_tokens: int = 32,
                 decode_strategy: str = "greedy_search", top_k: int = 0,
                 top_p: float = 1.0, temperature: float = 1.0,
                 num_beams: int = 4, length_penalty: float = 0.0,
                 eos_token_id=None, seed: int = 0, paged: bool = False,
                 page_size: int = 0, kv_dtype=None):
        """Autoregressive generation with a preallocated KV cache, as one
        jitted program (prefill + lax.scan decode loop).

        decode_strategy: 'greedy_search' | 'sampling' | 'beam_search'
        (the paddlenlp generate() surface; the reference era only has
        host-side beam_search ops, beam_search_op.cc). Returns
        (ids [B, max_new_tokens], scores [B]).

        ``paged=True`` routes through the paged-KV serving engine
        (paddle_tpu.serving) instead of the dense [B, S_max] cache:
        same weights via the cached decode state, page-granular cache
        HBM, fixed-shape decode ticks. Greedy paged output is bitwise
        identical to the dense path (the wrapper picks a page size
        dividing prompt+max_new so the attention reduction length
        matches); sampling draws from per-request key chains, so paged
        sampling is reproducible but not token-identical to the dense
        shared-batch rng. Beam search has no paged path.

        ``kv_dtype`` (paged only): the page pool's storage dtype —
        None keeps the model dtype (the bitwise contract above);
        'bf16' halves and 'int8' quarters cache HBM per token, at
        which point greedy parity becomes a measured token-match rate
        (serving docs), not a bitwise guarantee.
        """
        import numpy as _np

        from ..framework.tensor import Tensor as _T
        from ..ops import decoding as D

        ids_v = input_ids._value if isinstance(input_ids, _T) else \
            jnp.asarray(input_ids)   # accepts np arrays AND jax tracers
        b, t0 = ids_v.shape
        smax = t0 + max_new_tokens
        if smax > self.config.max_seq_len:
            raise ValueError(
                f"prompt {t0} + max_new_tokens {max_new_tokens} exceeds "
                f"max_seq_len {self.config.max_seq_len}")
        if decode_strategy not in ("greedy_search", "sampling",
                                   "beam_search"):
            raise ValueError(f"unknown decode_strategy {decode_strategy!r}")
        if paged:
            if decode_strategy == "beam_search":
                raise NotImplementedError(
                    "paged decode supports greedy_search/sampling; beam "
                    "reordering needs per-beam page aliasing (ROADMAP)")
            return self._generate_paged(
                _np.asarray(ids_v), max_new_tokens, decode_strategy,
                top_k, top_p, temperature, eos_token_id, seed, page_size,
                kv_dtype)
        if kv_dtype is not None:
            raise ValueError("kv_dtype is a paged-cache knob; the dense "
                             "cache follows the model dtype (use "
                             "paged=True)")
        stacked, other = self._decode_state()
        cfg = self.config
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        L = cfg.num_layers
        dt = other["embeddings.wte.weight"].dtype

        # jit cache: retracing the whole prefill+scan program per call
        # would cost seconds per generate() in a serving loop. Bounded:
        # a serving workload feeds this an open-ended stream of
        # (batch, len) shapes, so LRU-cap it and count evictions
        # (cache_evict/gpt_gen_jit in the profiler registry).
        jkey = (b, t0, max_new_tokens, decode_strategy, top_k, top_p,
                temperature, num_beams, length_penalty, eos_token_id,
                str(dt))
        if "_gen_jit" not in self.__dict__:
            from ..utils.lru import LRUCache

            self.__dict__["_gen_jit"] = LRUCache(GPT.GEN_JIT_CACHE_SIZE,
                                                 "gpt_gen_jit")
        jit_cache = self.__dict__["_gen_jit"]
        run = jit_cache.get(jkey)
        if run is None:
            def run_fn(stacked, other, tokens, rng):
                n = tokens.shape[0]
                ck = jnp.zeros((n, L, smax, nh, hd), dt)
                cv = jnp.zeros((n, L, smax, nh, hd), dt)
                logits, ck, cv = gpt_cached_apply(
                    cfg, stacked, other, ck, cv, tokens, 0)

                def step(cache, tok, pos):
                    ck, cv = cache
                    lg, ck, cv = gpt_cached_apply(
                        cfg, stacked, other, ck, cv, tok[:, None], pos)
                    return lg, (ck, cv)

                if decode_strategy == "beam_search":
                    cache = D.tile_cache_for_beams((ck, cv), num_beams)
                    return D.beam_search_decode(
                        step, cache, logits, t0, max_new_tokens,
                        num_beams, length_penalty=length_penalty,
                        eos_token_id=eos_token_id)
                if decode_strategy == "sampling":
                    ids, _ = D.sampling_decode(
                        step, (ck, cv), logits, t0, max_new_tokens, rng,
                        top_k=top_k, top_p=top_p, temperature=temperature,
                        eos_token_id=eos_token_id)
                else:
                    ids, _ = D.greedy_decode(
                        step, (ck, cv), logits, t0, max_new_tokens,
                        eos_token_id=eos_token_id)
                return ids, jnp.zeros((n,), jnp.float32)

            run = jax.jit(run_fn)
            jit_cache[jkey] = run

        ids, scores = run(stacked, other, ids_v, jax.random.PRNGKey(seed))
        return _T(ids), _T(scores)

    #: LRU capacity for the per-shape generate() executables
    GEN_JIT_CACHE_SIZE = 16
    #: LRU capacity for cached paged serving engines (paged=True path)
    PAGED_ENGINE_CACHE_SIZE = 4

    def _generate_paged(self, ids_np, max_new_tokens, decode_strategy,
                        top_k, top_p, temperature, eos_token_id, seed,
                        page_size, kv_dtype=None):
        """generate() surface over the paged serving engine: one slot
        per batch row, slot capacity == the dense path's S_max (the
        wrapper picks the largest page size <= 16 dividing S_max, so
        greedy output stays bitwise-identical to the dense cache)."""
        import numpy as _np

        from ..framework.tensor import Tensor as _T
        from ..serving import ServingConfig, ServingEngine

        b, t0 = ids_np.shape
        smax = t0 + max_new_tokens
        ps = page_size
        if not ps:
            ps = next(p for p in (16, 8, 4, 2, 1) if smax % p == 0)
        if smax % ps:
            raise ValueError(
                f"page_size {ps} must divide prompt+max_new_tokens "
                f"{smax} for the paged generate() path (bitwise parity "
                "needs slot capacity == dense S_max)")
        strategy = "sampling" if decode_strategy == "sampling" else "greedy"
        ekey = (b, t0, max_new_tokens, ps, strategy, top_k, top_p,
                temperature, eos_token_id, kv_dtype)
        if "_paged_engines" not in self.__dict__:
            from ..utils.lru import LRUCache

            self.__dict__["_paged_engines"] = LRUCache(
                GPT.PAGED_ENGINE_CACHE_SIZE, "gpt_paged_engine")
        engines = self.__dict__["_paged_engines"]
        eng = engines.get(ekey)
        if eng is None or eng._stacked is not self._decode_state()[0]:
            eng = ServingEngine(self, ServingConfig(
                num_slots=b, page_size=ps, pages_per_slot=smax // ps,
                prefill_chunk=t0, decode=strategy,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id, seed=seed,
                kv_dtype=kv_dtype))
            engines[ekey] = eng
        base = _np.asarray(jax.random.PRNGKey(seed))
        rids = [eng.submit(ids_np[i], max_new_tokens,
                           key=_np.asarray(jax.random.fold_in(base, i)))
                for i in range(b)]
        results = eng.run()
        out = _np.full((b, max_new_tokens),
                       eos_token_id if eos_token_id is not None else 0,
                       _np.int32)
        for i, rid in enumerate(rids):
            row = results[rid][:max_new_tokens]
            out[i, :row.shape[0]] = row
        eng.reset_results()
        return _T(jnp.asarray(out)), _T(jnp.zeros((b,), jnp.float32))

    def _decode_state(self):
        """Cached (stacked, other) decode params; rebuilt only when the
        underlying param values changed (training step replaces them)."""
        token = id(self.embeddings.wte.weight._value)
        cached = self.__dict__.get("_gen_state")
        if cached is not None and cached[0] == token:
            return cached[1], cached[2]
        stacked, other = _gpt_decode_state(self)
        self.__dict__["_gen_state"] = (token, stacked, other)
        return stacked, other

    def loss(self, tokens, labels=None):
        """Next-token LM loss (+ MoE load-balance aux when configured).
        labels default: tokens shifted left.

        Routes through the fused lm-head/CE (same kernel as
        pipeline_head): the [B, S, V] logits never materialize — the
        unfused forward()+cross_entropy spelling cost ~20% of the MoE
        bench step in f32 logit traffic (round-5 ablation)."""
        x = self.embeddings(tokens)
        for blk in self.blocks:
            x = blk(x)
        loss = self.pipeline_head(x, tokens, labels=labels)
        if self.config.moe_num_experts > 0:
            for blk in self.blocks:
                loss = loss + self.config.moe_aux_weight * blk.mlp.aux_loss
        return loss


def _ln(x, w, b, eps):
    m = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) / jnp.sqrt(var + eps) * w + b


def gpt_block_body(xc, p, eps, nh, hd, attend):
    """One pre-norm transformer block over stacked decode params ``p``,
    shared by the dense cached path (gpt_cached_apply) and the paged
    serving tick (serving/engine.py) — the two must stay BITWISE
    identical, so the block math lives in exactly one place and only the
    cache handling differs: ``attend(q, kk, vv) -> (o [n,t,nh,hd],
    extra)`` writes this layer's KV into its cache and attends."""
    n, t = xc.shape[0], xc.shape[1]
    h = nh * hd
    hn = _ln(xc, p["ln_1.weight"], p["ln_1.bias"], eps)
    qkv = hn @ p["attn.qkv_proj.weight"] + p["attn.qkv_proj.bias"]
    qkv = qkv.reshape(n, t, 3, nh, hd)
    q, kk, vv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o, extra = attend(q, kk, vv)
    o = o.reshape(n, t, h)
    xc = xc + o @ p["attn.out_proj.weight"] + p["attn.out_proj.bias"]
    h2 = _ln(xc, p["ln_2.weight"], p["ln_2.bias"], eps)
    mid = jax.nn.gelu(h2 @ p["mlp.fc_in.weight"] + p["mlp.fc_in.bias"],
                      approximate=True)
    xc = xc + mid @ p["mlp.fc_out.weight"] + p["mlp.fc_out.bias"]
    return xc, extra


def gpt_cached_apply(cfg: GPTConfig, stacked, other, ck, cv, tokens, pos0,
                     logits_index=None):
    """Pure-jax KV-cached forward for decoding (reference has no KV cache
    or generate() at all — its decoding is host-side beam_search ops,
    beam_search_op.cc; here decode is one compiled program).

    stacked: {block_suffix: [L, ...]} block params; other: {name: val};
    ck/cv: [N, L, S_max, NH, D] caches; tokens [N, T] processed at
    positions pos0..pos0+T. Returns (last-token logits [N, V], ck, cv).
    ``logits_index`` (may be traced): take logits at that query position
    instead of the last — the serving prefill pads prompts to a length
    bucket, so "last token" sits at true_len-1, not at T-1.

    Parity with GPT.forward is pinned by
    tests/test_generation.py::test_cached_prefill_matches_forward.
    """
    n, t = tokens.shape
    h = cfg.hidden_size
    nh = cfg.num_heads
    hd = h // nh
    eps = cfg.layer_norm_eps
    wte = other["embeddings.wte.weight"]
    wpe = other["embeddings.wpe.weight"]
    pos = pos0 + jnp.arange(t)
    x = wte[tokens] + wpe[pos][None]
    smax = ck.shape[2]
    key_pos = jnp.arange(smax)
    # causal-with-cache mask: query i sees cache positions <= pos0 + i
    mask = key_pos[None, None, None, :] <= \
        (pos0 + jnp.arange(t))[None, None, :, None]

    ckl = jnp.swapaxes(ck, 0, 1)            # [L, N, S, NH, D]
    cvl = jnp.swapaxes(cv, 0, 1)

    def block(xc, inp):
        p, k_c0, v_c0 = inp

        def attend(q, kk, vv):
            k_c = jax.lax.dynamic_update_slice(k_c0, kk, (0, pos0, 0, 0))
            v_c = jax.lax.dynamic_update_slice(v_c0, vv, (0, pos0, 0, 0))
            att = jnp.einsum("btnd,bsnd->bnts", q, k_c) / math.sqrt(hd)
            att = jnp.where(mask, att, -1e9)
            w = jax.nn.softmax(att.astype(jnp.float32),
                               axis=-1).astype(xc.dtype)
            return jnp.einsum("bnts,bsnd->btnd", w, v_c), (k_c, v_c)

        return gpt_block_body(xc, p, eps, nh, hd, attend)

    x, (ckl, cvl) = jax.lax.scan(block, x, (stacked, ckl, cvl))
    x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
    if logits_index is None:
        last = x[:, -1]
    else:
        last = jax.lax.dynamic_index_in_dim(x, logits_index, axis=1,
                                            keepdims=False)
    if "lm_head.weight" in other:
        logits = last @ other["lm_head.weight"]
    else:
        logits = last @ wte.T
    return logits, jnp.swapaxes(ckl, 0, 1), jnp.swapaxes(cvl, 0, 1)


def gpt_ragged_apply(cfg: GPTConfig, stacked, other, kpool, vpool,
                     tokens, tok_pos, tok_limit, row_tab, row_pos0,
                     row_len, sample_ix, decode_rows: int,
                     chunk_width: int, impl: str = "xla",
                     spec_k: int = 0, kscale=None, vscale=None):
    """Mixed prefill/decode forward over the PAGED cache: every token
    in flight rides one program. ``tokens`` [NT] is the flat token
    buffer of one serving tick — ``decode_rows`` resident decode
    tokens followed by the prefill chunks, ``chunk_width`` tokens
    each; which is which is *only* metadata:

    tok_pos    [NT] int32   absolute cache position of each token
    tok_limit  [NT] int32   first non-writable position of the token's
                            sequence — KV writes at ``tok_pos >=
                            tok_limit`` route to the null page (decode
                            rows: the slot capacity, so an
                            exact-capacity rider never stomps its own
                            published tail page; prefill rows: the
                            true prompt length, so chunk padding never
                            lands in a page a neighbour aliases; pad
                            rows: 0)
    row_tab    [R, NPs]     page-table row per ragged attention row,
                            R = decode_rows + num_chunks (pad chunk
                            rows: all-null tables)
    row_pos0   [R] int32    first query position of each row
    row_len    [R] int32    real queries per row (decode rows: 1)
    sample_ix  [S] int32    flat indices whose final hidden states
                            feed the logits head (one per emitter)

    Hidden-state compute (embeddings, LN, QKV/MLP matmuls) runs once
    over the flat buffer; each token's KV is scattered to its own
    page/offset; attention routes through the ONE
    ``ragged_paged_attention`` entry point, with rows grouped by their
    static query width — decode rows as ``[decode_rows, 1]`` and chunk
    rows as ``[num_chunks, chunk_width]`` — so a decode-only tick pays
    the pre-unification decode gather cost, not ``chunk_width×`` pad
    queries ("Ragged Paged Attention", PAPERS.md: per-row
    ``(pos0, true_len)`` metadata; the width grouping is the XLA-
    friendly layout of the same raggedness, and the Pallas kernel
    underneath handles either width in one grid). All metadata may be
    traced: one compiled program serves every mix of resident decodes
    and prompt chunks. Returns (logits [S, V], kpool, vpool).

    ``spec_k > 0`` (speculative decoding, serving/spec.py) widens each
    of the ``decode_rows`` slot rows into a **verify row** of
    ``1 + spec_k`` tokens: the flat buffer becomes ``decode_rows`` last
    tokens, then ``decode_rows * spec_k`` draft tokens (slot-major),
    then the chunks. The slot rows' attention groups as
    ``[decode_rows, 1 + spec_k]``; logits can be sampled at EVERY
    verify position (a verify row is exactly a chunk-shaped row whose
    logits are kept per position, not just at the end). A slot that is
    not speculating this tick rides the same group with
    ``row_len == 1`` — its draft positions are pad queries
    (``tok_limit == 0`` routes their KV writes to the null page).

    Bitwise contract (the engine's parity tests rest on it):
    per-token results are independent of which *other* rows share the
    program — hidden/head contractions are row-independent, LN/GELU
    are elementwise, and attention always reduces over the full slot
    capacity with exact-zero masked weights (``ops/paged_attention._
    gather_attend``, the one shared spelling) — so a decode row here
    equals the old dedicated decode tick and a chunk row equals the
    old suffix-prefill program, token for token, bit for bit; a verify
    position equals the decode row the non-speculative engine would
    have run at that position.

    ``kscale``/``vscale`` [L, P, NH] (ISSUE 12): per-page per-head
    scales of an int8 pool. When given, every token's KV write routes
    through ``ops/paged_attention.paged_kv_scatter`` (quantize at the
    page's running-max scale, re-quantizing resident content when it
    grows) and the attention gather dequantizes with the same scales —
    the whole int8 story lives in those two shared helpers, so both
    attention impls and every delegating spelling inherit it. The
    return grows to (logits, kpool, vpool, kscale, vscale); numerics
    are tolerance, not bitwise, vs the unquantized pool (the engine
    only asserts bitwise between two int8 engines).
    """
    from ..ops.paged_attention import (paged_kv_scatter,
                                      ragged_paged_attention)

    nt = tokens.shape[0]
    nd = decode_rows
    base = nd * (1 + spec_k)
    nch = (nt - base) // chunk_width if chunk_width else 0
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_eps
    ps = kpool.shape[2]
    nps = row_tab.shape[1]
    wte = other["embeddings.wte.weight"]
    wpe = other["embeddings.wpe.weight"]
    x = wte[tokens[:, None]] + wpe[tok_pos[:, None]]    # [NT, 1, h]
    # token -> ragged row (static: the flat layout never changes);
    # draft tokens share their slot's row (same page table)
    parts = [jnp.arange(nd, dtype=jnp.int32)]
    if spec_k:
        parts.append(jnp.repeat(jnp.arange(nd, dtype=jnp.int32), spec_k))
    if nch:
        parts.append(jnp.repeat(nd + jnp.arange(nch, dtype=jnp.int32),
                                chunk_width))
    tok_row = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    # write targets: real positions go to their slot page, everything
    # at/past the limit to the null page (clip keeps the page-table
    # index in range for positions past the slot capacity)
    page = jnp.where(
        tok_pos < tok_limit,
        row_tab[tok_row, jnp.minimum(tok_pos // ps, nps - 1)],
        0)
    off = tok_pos % ps

    quantized = kscale is not None

    def block(xc, inp):
        if quantized:
            p, kpl0, vpl0, ksl0, vsl0 = inp
        else:
            p, kpl0, vpl0 = inp
            ksl0 = vsl0 = None

        def attend(q, kk, vv):
            kpl, ksl = paged_kv_scatter(kpl0, ksl0, page, off, kk[:, 0])
            vpl, vsl = paged_kv_scatter(vpl0, vsl0, page, off, vv[:, 0])
            outs = []
            if nd and spec_k:
                # verify grouping [nd, 1 + spec_k]: each slot's last
                # token plus its drafts as one chunk-shaped row; the
                # outputs un-interleave back into flat-buffer order
                qv = jnp.concatenate(
                    [q[:nd], q[nd:base, 0].reshape(nd, spec_k, nh, hd)],
                    axis=1)
                ov = ragged_paged_attention(
                    qv, kpl, vpl, row_tab[:nd], row_pos0[:nd],
                    row_len[:nd], impl=impl, k_scale=ksl, v_scale=vsl)
                outs.append(ov[:, :1])
                outs.append(ov[:, 1:].reshape(nd * spec_k, 1, nh, hd))
            elif nd:
                outs.append(ragged_paged_attention(
                    q[:nd], kpl, vpl, row_tab[:nd], row_pos0[:nd],
                    row_len[:nd], impl=impl, k_scale=ksl, v_scale=vsl))
            if nch:
                qp = q[base:, 0].reshape(nch, chunk_width, nh, hd)
                op = ragged_paged_attention(
                    qp, kpl, vpl, row_tab[nd:], row_pos0[nd:],
                    row_len[nd:], impl=impl, k_scale=ksl, v_scale=vsl)
                outs.append(op.reshape(nch * chunk_width, 1, nh, hd))
            o = outs[0] if len(outs) == 1 else \
                jnp.concatenate(outs, axis=0)
            return (o, (kpl, vpl, ksl, vsl)) if quantized \
                else (o, (kpl, vpl))

        return gpt_block_body(xc, p, eps, nh, hd, attend)

    if quantized:
        x, (kpool, vpool, kscale, vscale) = jax.lax.scan(
            block, x, (stacked, kpool, vpool, kscale, vscale))
    else:
        x, (kpool, vpool) = jax.lax.scan(block, x,
                                         (stacked, kpool, vpool))
    x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
    last = x[sample_ix, 0]                              # [S, h]
    if "lm_head.weight" in other:
        logits = last @ other["lm_head.weight"]
    else:
        logits = last @ wte.T
    if quantized:
        return logits, kpool, vpool, kscale, vscale
    return logits, kpool, vpool


def gpt_paged_suffix_apply(cfg: GPTConfig, stacked, other, kpool, vpool,
                           tokens, pos0, true_len, page_row,
                           logits_index, kscale=None, vscale=None):
    """Suffix-prefill forward over the PAGED cache: one prompt chunk
    ``tokens`` [1, T] at positions pos0..pos0+T-1 of the slot whose
    page-table row is ``page_row`` [NPs]. Retired into the unified
    ragged call — each chunk position becomes one ragged row of
    ``gpt_ragged_apply`` (bitwise-identical per position, see its
    contract); kept as the legacy two-dispatch engine mode's prefill
    program and as the documented single-slot chunk surface.
    ``pos0``/``true_len``/``logits_index`` may be traced. Returns
    (logits at chunk index ``logits_index`` [1, V], kpool, vpool).
    """
    t = tokens.shape[1]
    tok_pos = pos0 + jnp.arange(t)
    tok_limit = jnp.broadcast_to(true_len, (t,))
    sample_ix = jnp.asarray(logits_index, jnp.int32)[None]
    return gpt_ragged_apply(cfg, stacked, other, kpool, vpool,
                            tokens[0], tok_pos, tok_limit,
                            page_row[None],
                            jnp.asarray(pos0, jnp.int32)[None],
                            jnp.full((1,), t, jnp.int32), sample_ix,
                            decode_rows=0, chunk_width=t,
                            kscale=kscale, vscale=vscale)


def _gpt_decode_state(model: "GPT"):
    """(stacked {sfx: [L, ...]}, other {name: val}) jnp dicts from the
    eager model, for gpt_cached_apply."""
    from ..static.functional import state_tensors

    if model.config.moe_num_experts:
        raise NotImplementedError(
            "generate() supports dense GPT; MoE decode needs expert "
            "routing in the cached path")
    blocks = list(model.blocks)
    sfx, t0 = state_tensors(blocks[0])[:2]
    per_block = [state_tensors(b)[1] for b in blocks]   # one walk per block
    stacked = {s: jnp.stack([pb[j]._value for pb in per_block], 0)
               for j, s in enumerate(sfx)}
    pn, pt, _, _ = state_tensors(model)
    block_ids = {id(x) for pb in per_block for x in pb}
    other = {n: p._value for n, p in zip(pn, pt) if id(p) not in block_ids}
    return stacked, other


class GPTForGeneration(nn.Layer):
    """Export wrapper: forward(tokens) runs the full generate loop, so
    ``paddle_tpu.jit.save`` serializes prefill + KV-cached decode as ONE
    jax.export artifact runnable in a fresh process (the reference's
    save_inference_model + beam-search-ops analogue, done compiler-side)."""

    def __init__(self, gpt: GPT, max_new_tokens: int = 16,
                 decode_strategy: str = "greedy_search", **gen_kw):
        super().__init__()
        self.gpt = gpt
        self.max_new_tokens = max_new_tokens
        self.decode_strategy = decode_strategy
        self.gen_kw = gen_kw

    def forward(self, tokens):
        ids, _ = self.gpt.generate(tokens,
                                   max_new_tokens=self.max_new_tokens,
                                   decode_strategy=self.decode_strategy,
                                   **self.gen_kw)
        return ids


def gpt_tiny(**kw):
    """Small config for tests/dryrun."""
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, **kw)
    return GPT(cfg)
