"""GPT model family — the flagship for the hybrid-parallel north star
(BASELINE.md: GPT-3 1.3B/13B, TP×PP×sharding, ≥45% MFU target).

The reference has no GPT in-tree (its GPT configs ran via fleet meta
optimizers over user model code); here the model is first-class and
TPU-first:
  - attention through F.scaled_dot_product_attention (flash path),
  - q/kv/mlp projections as tensor-parallel layers carrying PartitionSpecs
    (distributed/parallel_layers.py) that the strategy compiler turns into
    GSPMD shardings,
  - identical block structure per layer so the compiled path can stack
    block params into [L, ...] arrays and lax.scan over layers (and shard
    the stage axis for pipeline parallelism).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
from jax.sharding import PartitionSpec as P

from .. import nn
from ..distributed import context as _dctx
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import arange


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_hidden_size: int = 0          # default 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # MoE (beyond-reference capability, distributed/moe.py): >0 replaces
    # every block's FFN with a num-experts MoE sharded over 'ep'
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if not self.ffn_hidden_size:
            self.ffn_hidden_size = 4 * self.hidden_size

    # presets from the reference north-star table (BASELINE.md)
    @staticmethod
    def gpt3_125m():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt3_350m():
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def gpt3_1_3b():
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_seq_len=2048)

    @staticmethod
    def gpt3_6_7b():
        return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                         max_seq_len=2048)

    @staticmethod
    def gpt3_13b():
        return GPTConfig(hidden_size=5120, num_layers=40, num_heads=40,
                         max_seq_len=2048)

    def num_params(self) -> int:
        h, L, v = self.hidden_size, self.num_layers, self.vocab_size
        per_block = 4 * h * h + 2 * h * self.ffn_hidden_size + 13 * h
        return v * h + self.max_seq_len * h + L * per_block + 2 * h

    def flops_per_token(self, seq_len=None) -> float:
        """Training FLOPs/token ≈ 6N + 12·L·h·s (attention term)."""
        s = seq_len or self.max_seq_len
        return 6.0 * self.num_params() + 12.0 * self.num_layers * \
            self.hidden_size * s


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range /
                            math.sqrt(2 * c.num_layers))
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, weight_attr=init,
            gather_output=False)
        self.out_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, weight_attr=out_init)
        self.dropout = c.dropout
        # qkv weight columns interleave q|k|v: shard on out dim stays valid
        self.qkv_proj.param_shardings = {"weight": P(None, "tp"),
                                         "bias": P("tp")}

    def forward(self, x):
        b, s, h = x.shape[0], x.shape[1], x.shape[2]
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)
        sp = _dctx.current_sequence_parallel()
        dropout_active = bool(self.dropout) and self.training
        if sp is not None:
            # sequence-parallel: ring attention over the 'sp' mesh axis
            # (ops/ring_attention.py) — seq dim stays sharded end to end.
            # Attention-prob dropout is not expressible in the ring (probs
            # never materialize): under sp it must be off. Inside the
            # manual region there is NO correct fallback (plain attention
            # would be block-diagonal over the local shard), so raise.
            from ..ops.ring_attention import (_ring_mha,
                                              sequence_parallel_attention)
            from ..tensor._helper import apply

            mesh, axis, manual = sp
            if dropout_active:
                raise NotImplementedError(
                    "attention-probability dropout is not supported under "
                    "sequence parallelism (ring attention); set "
                    "GPTConfig.dropout=0 or sp_degree=1")
            if manual:
                # already inside a shard_map manual over `axis`
                fn = lambda q_, k_, v_: _ring_mha(q_, k_, v_, True, None,
                                                  axis)
            else:
                fn = lambda q_, k_, v_: sequence_parallel_attention(
                    q_, k_, v_, mesh, causal=True, axis_name=axis)
            out = apply(fn, q, k, v, name="ring_attention")
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range /
                            math.sqrt(2 * c.num_layers))
        self.fc_in = ColumnParallelLinear(c.hidden_size, c.ffn_hidden_size,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(c.ffn_hidden_size, c.hidden_size,
                                        weight_attr=out_init)
        self.dropout = c.dropout

    def forward(self, x):
        x = F.gelu(self.fc_in(x), approximate=True)
        x = self.fc_out(x)
        return F.dropout(x, self.dropout, training=self.training)


class GPTBlock(nn.Layer):
    """Pre-norm transformer block; identical structure per layer (stackable)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        if config.moe_num_experts > 0:
            from ..distributed.moe import MoEMLP

            self.mlp = MoEMLP(config.hidden_size, config.ffn_hidden_size,
                              config.moe_num_experts,
                              top_k=config.moe_top_k,
                              capacity_factor=config.moe_capacity_factor,
                              initializer_range=config.initializer_range)
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.wte = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size,
            weight_attr=I.Normal(0.0, c.initializer_range))
        self.wpe = nn.Embedding(
            c.max_seq_len, c.hidden_size,
            weight_attr=I.Normal(0.0, c.initializer_range))
        self.dropout = c.dropout

    def forward(self, tokens):
        s = tokens.shape[1]
        pos = arange(0, s, dtype="int64").unsqueeze(0)
        x = self.wte(tokens) + self.wpe(pos)
        return F.dropout(x, self.dropout, training=self.training)


class GPT(nn.Layer):
    """Decoder-only GPT. ``forward`` returns logits; ``loss`` computes the
    shifted next-token cross entropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                weight_attr=I.Normal(0.0, config.initializer_range),
                gather_output=True)

    def forward(self, tokens):
        x = self.embeddings(tokens)
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        if self.config.tie_word_embeddings:
            from ..tensor import matmul

            return matmul(x, self.embeddings.wte.weight, transpose_y=True)
        return self.lm_head(x)

    # --- pipeline protocol (distributed/hybrid.py) -----------------------
    def pipeline_stem(self, tokens):
        return self.embeddings(tokens)

    def pipeline_blocks(self):
        return self.blocks

    def pipeline_head(self, x, tokens):
        """Final norm + fused lm-head/CE (ops/fused_ce.py): the [B,S,V]
        logits never materialize in HBM."""
        from ..ops.fused_ce import fused_linear_cross_entropy

        x = self.ln_f(x)
        # chunking over seq would fight an sp sharding; sp>1 runs one chunk
        chunk = None if _dctx.current_sequence_parallel() else 256
        if self.config.tie_word_embeddings:
            return fused_linear_cross_entropy(
                x, self.embeddings.wte.weight, tokens, chunk=chunk,
                next_token=True)
        return fused_linear_cross_entropy(
            x, self.lm_head.weight, tokens, chunk=chunk, transpose_w=True,
            next_token=True)

    def loss(self, tokens, labels=None):
        """Next-token LM loss (+ MoE load-balance aux when configured).
        labels default: tokens shifted left."""
        logits = self.forward(tokens)
        if labels is None:
            lg = logits[:, :-1]
            lb = tokens[:, 1:]
        else:
            lg, lb = logits, labels
        b, s = lb.shape[0], lb.shape[1]
        loss = F.cross_entropy(lg.reshape([b * s, -1]),
                               lb.reshape([b * s]))
        if self.config.moe_num_experts > 0:
            for blk in self.blocks:
                loss = loss + self.config.moe_aux_weight * blk.mlp.aux_loss
        return loss


def gpt_tiny(**kw):
    """Small config for tests/dryrun."""
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, **kw)
    return GPT(cfg)
