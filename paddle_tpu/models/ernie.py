"""ERNIE model family — the ZeRO-3 + recompute north-star config
(BASELINE.md: "ERNIE-3.0-style 10B, ZeRO-3 + recompute").

The reference has no ERNIE in-tree either (its ERNIE runs were user model
code over the fluid transformer layers; the repo only carries the fleet
machinery they trained with — sharding_optimizer.py, recompute_optimizer.py).
Architecturally ERNIE is a BERT-style bidirectional encoder with MLM-family
pretraining heads, so the TPU-native implementation shares the BERT blocks
(models/bert.py) — identical stackable structure, tensor-parallel
projections — under ERNIE's configs, and goes through the same pipeline
protocol (distributed/hybrid.py) with ZeRO-3 + recompute strategy.
"""
from __future__ import annotations

from dataclasses import dataclass

from .bert import BertConfig, BertForPretraining


@dataclass
class ErnieConfig(BertConfig):
    vocab_size: int = 18000           # ERNIE zh vocab
    max_seq_len: int = 512
    type_vocab_size: int = 4          # ERNIE uses more segment types

    @staticmethod
    def ernie_base():
        return ErnieConfig()

    @staticmethod
    def ernie_large():
        return ErnieConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def ernie_10b_style():
        """ERNIE-3.0-style dense trunk (the BASELINE.md ZeRO-3 config)."""
        return ErnieConfig(hidden_size=4096, num_layers=48, num_heads=64,
                           vocab_size=40000)


class ErnieForPretraining(BertForPretraining):
    """ERNIE pretraining trunk + MLM/NSP-style heads. Knowledge-masking is
    a DATA-side strategy (whole-word/entity mask spans arrive as
    mlm_labels); the model side is the shared encoder."""

    def __init__(self, config: ErnieConfig):
        super().__init__(config)


def ernie_tiny(**kw):
    """Small config for tests."""
    cfg = ErnieConfig(vocab_size=128, hidden_size=64, num_layers=4,
                      num_heads=4, max_seq_len=64, **kw)
    return ErnieForPretraining(cfg)
