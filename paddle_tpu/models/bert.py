"""BERT model family — the DP+AMP north-star config (BASELINE.md:
"BERT-base pretraining, DP + AMP(bf16), tokens/sec/chip + loss curve").

The reference has no BERT in-tree (its BERT runs were user model code over
nn.TransformerEncoder, reference python/paddle/nn/layer/transformer.py);
here it is first-class and TPU-first, mirroring the GPT design
(models/gpt.py): tensor-parallel projections carrying PartitionSpecs,
identical block structure per layer (stackable for lax.scan / pipeline),
flash attention for the bidirectional self-attention when no padding mask
is supplied.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from jax.sharding import PartitionSpec as P

from .. import nn
from ..distributed.parallel_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import arange


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: int = 0            # default 4*hidden
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    # MLM head gather width: project only the top-`max_predictions`
    # masked positions onto the vocab instead of the full sequence
    # (reference: create_pretraining_data's masked_lm_positions arrays,
    # max_predictions_per_seq=80 at seq 512 — the reference NEVER runs
    # the vocab projection on unmasked positions either; its data
    # pipeline materializes the gather). 0 = full-sequence head.
    max_predictions: int = 0

    def __post_init__(self):
        if not self.ffn_hidden_size:
            self.ffn_hidden_size = 4 * self.hidden_size

    @staticmethod
    def bert_base():
        return BertConfig()

    @staticmethod
    def bert_large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16)

    def num_params(self) -> int:
        h, L = self.hidden_size, self.num_layers
        per_block = 4 * h * h + 2 * h * self.ffn_hidden_size + 13 * h
        emb = (self.vocab_size + self.max_seq_len +
               self.type_vocab_size) * h
        return emb + L * per_block + 2 * h

    def flops_per_token(self, seq_len=None) -> float:
        s = seq_len or self.max_seq_len
        return 6.0 * self.num_params() + 12.0 * self.num_layers * \
            self.hidden_size * s


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, weight_attr=init,
            gather_output=False)
        self.qkv_proj.param_shardings = {"weight": P(None, "tp"),
                                         "bias": P("tp")}
        self.out_proj = RowParallelLinear(c.hidden_size, c.hidden_size,
                                          weight_attr=init)
        self.dropout = c.dropout

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape[0], x.shape[1], x.shape[2]
        qkv = self.qkv_proj(x)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.dropout, training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class BertBlock(nn.Layer):
    """Post-norm encoder block (BERT convention); identical structure per
    layer so the compiled path can stack params."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        self.attn = BertSelfAttention(c)
        self.ln_1 = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(c.hidden_size, c.ffn_hidden_size,
                                          weight_attr=init,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(c.ffn_hidden_size, c.hidden_size,
                                        weight_attr=init)
        self.ln_2 = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = c.dropout

    def forward(self, x, attn_mask=None):
        a = self.attn(x, attn_mask)
        x = self.ln_1(x + F.dropout(a, self.dropout,
                                    training=self.training))
        m = self.fc_out(F.gelu(self.fc_in(x)))
        return self.ln_2(x + F.dropout(m, self.dropout,
                                       training=self.training))


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        self.word = VocabParallelEmbedding(c.vocab_size, c.hidden_size,
                                           weight_attr=init)
        self.position = nn.Embedding(c.max_seq_len, c.hidden_size,
                                     weight_attr=init)
        self.token_type = nn.Embedding(c.type_vocab_size, c.hidden_size,
                                       weight_attr=init)
        self.ln = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = c.dropout

    def forward(self, tokens, token_type_ids=None):
        s = tokens.shape[1]
        pos = arange(0, s, dtype="int64").unsqueeze(0)
        x = self.word(tokens) + self.position(pos)
        if token_type_ids is not None:
            x = x + self.token_type(token_type_ids)
        return F.dropout(self.ln(x), self.dropout, training=self.training)


class Bert(nn.Layer):
    """Encoder stack; returns (sequence_output, pooled_output)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.blocks = nn.LayerList([BertBlock(config)
                                    for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, tokens, token_type_ids=None, attn_mask=None):
        x = self.embeddings(tokens, token_type_ids)
        for blk in self.blocks:
            x = blk(x, attn_mask)
        from ..tensor import tanh

        pooled = tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (the BERT pretraining objective). ``loss`` takes
    (tokens, token_type_ids, mlm_labels, nsp_labels); mlm_labels use -100
    for unmasked positions (ignored)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        self.bert = Bert(c)
        self.mlm_transform = nn.Linear(c.hidden_size, c.hidden_size)
        self.mlm_ln = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.mlm_bias = self.create_parameter(
            [c.vocab_size], default_initializer=I.Constant(0.0))
        self.nsp_head = nn.Linear(c.hidden_size, 2)

    def forward(self, tokens, token_type_ids=None, attn_mask=None):
        seq, pooled = self.bert(tokens, token_type_ids, attn_mask)
        from ..tensor import matmul

        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        # tied decoder: project onto the word-embedding matrix
        mlm_logits = matmul(h, self.bert.embeddings.word.weight,
                            transpose_y=True) + self.mlm_bias
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    # --- pipeline protocol (distributed/hybrid.py) -----------------------
    def pipeline_stem(self, tokens, token_type_ids, mlm_labels, nsp_labels):
        return self.bert.embeddings(tokens, token_type_ids)

    def pipeline_blocks(self):
        return self.bert.blocks

    def pipeline_head(self, x, tokens, token_type_ids, mlm_labels,
                      nsp_labels):
        """MLM via the fused tied-decoder CE + NSP on the pooled output.

        With ``config.max_predictions`` set, the masked positions are
        gathered FIRST (top_k on the mask — jittable, static shapes) and
        only those run the transform + vocab projection: at a 15% mask
        rate this removes ~85% of the head flops, exactly like the
        reference's masked_lm_positions pipeline. Equal to the
        full-sequence ignore-index CE whenever no row has more than
        max_predictions masked positions (excess positions are dropped,
        mirroring the reference data generator's truncation)."""
        from ..distributed import context as _dctx
        from ..ops.fused_ce import fused_linear_cross_entropy
        from ..tensor import take_along_axis, tanh, topk, where
        from ..tensor.creation import full_like

        cls = x[:, 0]                    # CLS BEFORE any gather: NSP must
        maxp = int(getattr(self.config, "max_predictions", 0) or 0)
        if maxp and maxp < int(mlm_labels.shape[1]):
            is_masked = (mlm_labels != -100).astype("int32")
            score, pos = topk(is_masked, maxp, axis=1)
            x = take_along_axis(x, pos.unsqueeze(-1), axis=1)
            mlm_labels = where(score > 0,
                               take_along_axis(mlm_labels, pos, axis=1),
                               full_like(score, -100))
        h = self.mlm_ln(F.gelu(self.mlm_transform(x)))
        chunk = None if _dctx.current_sequence_parallel() else 256
        mlm = fused_linear_cross_entropy(
            h, self.bert.embeddings.word.weight, mlm_labels,
            bias=self.mlm_bias, chunk=chunk)
        pooled = tanh(self.bert.pooler(cls))
        nsp = F.cross_entropy(self.nsp_head(pooled).astype("float32"),
                              nsp_labels)
        return mlm + nsp

    def loss(self, tokens, token_type_ids, mlm_labels, nsp_labels):
        """Same objective as pipeline_head (fused tied-decoder CE +
        masked-position gather): the [B, S, V] logits never materialize
        here either."""
        x = self.bert.embeddings(tokens, token_type_ids)
        for blk in self.bert.blocks:
            x = blk(x)
        return self.pipeline_head(x, tokens, token_type_ids, mlm_labels,
                                  nsp_labels)


def bert_tiny(**kw):
    """Small config for tests."""
    cfg = BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=64, type_vocab_size=2, **kw)
    return BertForPretraining(cfg)
