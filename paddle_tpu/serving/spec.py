"""Speculative decoding on the paged serving engine (ROADMAP item 4).

Decode throughput on the unified tick is bounded by one target-model
dispatch per emitted token. Classic speculative decoding amortizes that
cost: a small **draft model** runs ``k`` tokens ahead per resident
slot, then ONE target **verify tick** scores every slot's
``k + 1``-token row through the existing mixed-row ragged program
(``models/gpt.py::gpt_ragged_apply`` with ``spec_k`` — a verify row is
exactly a prefill-chunk-shaped row whose logits are kept at every
position, not just the last). Greedy acceptance takes the longest
prefix where draft == target argmax, plus one correction token; the
emitted stream is therefore always the TARGET's own argmax stream, so
greedy speculative output is **bitwise identical** to non-speculative
greedy paged decode (which is itself bitwise vs dense ``generate()``)
— the classic invariant, and this engine's signature parity-contract
style (tests/test_spec_decode.py pins it across admission orders,
prefix-cache hits, COW divergence, preemption/requeue mid-speculation,
and exact-capacity finishes).

Two compiled dispatch sites, each tracing exactly once
(``ServingEngine.compiled_sites`` == {draft tick, verify/mixed tick}):

- **Draft tick** (``make_draft_tick``): the draft model keeps a DENSE
  per-slot KV cache ``[L_d, num_slots, capacity + 1, NH_d, D_d]``
  (builder's call per the issue — dense is the simple footprint;
  position ``capacity`` is the trash column, the dense analogue of the
  page pool's null page: pad/overflow writes land there, never in live
  state). One fixed-shape program does BOTH draft duties per scheduler
  step: a ``feed`` stage catches slots' draft caches up to the
  target's accepted frontier (prompt tokens after admission or a
  prefix-cache hit — the draft has no prefix cache — and the one
  token the draft never saw after a full-acceptance round), then a
  ``generate`` stage scans ``k`` greedy draft steps. Each stage sits
  behind its own ``lax.cond`` — steady-state ticks (nothing to feed)
  pay only the k-step scan, and feed-only ticks (chunked prefill in
  flight) skip the generate scan — the engine's decode-only
  fast-path idiom on both axes.
- **Verify tick** (``make_spec_tick``): the unified mixed-row tick
  widened with a draft-token section. Flat token layout
  ``[ns last_tok | ns*k drafts | chunks]``; slot rows group as
  ``[ns, 1+k]`` ragged rows (a non-speculating slot rides with
  ``row_len == 1`` — its draft positions are pad queries whose writes
  route to the null page). Four ``lax.cond`` branches in ONE
  executable extend the decode-only fast path: with speculation idle
  (no drafts) and/or no chunks aboard, the tick pays exactly the
  non-speculative program's capacity — verify rows cost nothing while
  nobody speculates. Greedy argmax and acceptance
  (``ops/decoding.spec_accept_length``) run on device; the host
  materializes ``(tokens [ns, 1+k], accepted [ns])`` once per tick.

**Rewind** is what the PR-5 refcount/COW machinery makes safe: the
rejected tail's KV writes land in pages only this slot holds (prefix
pages are published strictly BEHIND the accepted frontier), so the
engine just truncates ``pos`` and returns pages past the new length
(``PagePool.shrink_slot``); the draft cache needs no repair either —
its own speculation wrote the accepted tokens' KV, and the correction
token arrives as the next round's ``gen_tok``. Preemption resets the
slot's draft frontier to 0; the requeued prompt (with generated
prefix) re-feeds chunk-by-chunk, so the draft state survives
preemption/requeue by reconstruction, not by copy.

**Why host sync per verify tick**: acceptance decides the next tick's
positions and page growth, which are host scheduling state — the
deferred-sync window of the plain engine cannot stay open across an
acceptance decision. Spec mode trades the PR-3 overlap for a k-token
amortization per dispatch; ``serving/spec_accept_rate`` and
``serve_bench --spec-decode`` measure whether the trade pays.

Residue (ROADMAP): greedy only — sampling needs the rejection-sampling
acceptance rule; the draft cache is dense, not paged. (The "k is
static per engine" line is retired: ``SpecConfig.adaptive`` drives a
per-slot depth from an accept-rate EWMA — ISSUE 15,
serving/sched.py::SpecKController.)
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..profiler import recompile as _recompile

__all__ = ["SpecConfig", "DraftRunner", "make_draft_tick",
           "make_spec_tick"]


@dataclass
class SpecConfig:
    """Speculative-decoding knobs for ``ServingConfig.spec``.

    ``draft_model``: a dense ``GPT`` sharing the target's vocab (and
    ``max_seq_len >= target's``) — typically much smaller; quality only
    affects the accept rate, never correctness (rejected drafts cost a
    wasted verify position, accepted ones skip a target dispatch).
    ``k``: draft tokens speculated per verify tick; each slot's actual
    depth is clamped per tick by its remaining token budget and page
    headroom (down to 0 = a plain decode row).
    ``adaptive`` (ISSUE 15; serving/sched.py::SpecKController): drive
    each slot's depth from an accept-rate EWMA (alpha ``ewma_alpha``)
    instead of always offering the full ``k`` — high-accept slots run
    full depth, low-accept slots decay toward 0 (a plain decode row),
    all inside the compiled ``[0, k]`` range the verify tick already
    supports via ``row_len``, so neither compiled site changes.
    ``reprobe_every`` (ISSUE 16 satellite): a slot stuck at depth 0
    re-probes at depth 1 every this-many draft ticks, so a recovered
    accept rate regains speculation (0 disables — the PR 15 sticky
    behavior)."""

    draft_model: object
    k: int = 4
    adaptive: bool = False
    ewma_alpha: float = 0.5
    reprobe_every: int = 64


class DraftRunner:
    """Draft-model state + the ONE jitted draft tick.

    Host side: ``len[s]`` is the slot's draft frontier (dense-cache
    positions ``0..len[s]-1`` hold the accepted sequence's KV). Device
    side: the dense caches, donated per dispatch. The engine owns
    scheduling (what to feed, who generates) and frontier bookkeeping;
    this class owns the state and the compiled program."""

    def __init__(self, draft_model, num_slots: int, capacity: int,
                 k: int, feed_width: int):
        cfg = draft_model.config
        self.config = cfg
        self.k = int(k)
        self.capacity = int(capacity)
        self.feed_width = int(feed_width)
        self.stacked, self.other = draft_model._decode_state()
        dt = self.other["embeddings.wte.weight"].dtype
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        shape = (cfg.num_layers, num_slots, capacity + 1, nh, hd)
        self.kc = jnp.zeros(shape, dt)
        self.vc = jnp.zeros(shape, dt)
        self.len = np.zeros(num_slots, np.int64)
        self.site = _recompile.unique_site("serving.draft")
        self.tick = jax.jit(
            make_draft_tick(cfg, num_slots, capacity, k, feed_width,
                            self.site),
            donate_argnums=(2, 3))

    def reset_slot(self, slot: int) -> None:
        """Invalidate the slot's draft cache (admission / finish /
        preemption): the next tenant re-feeds from position 0."""
        self.len[slot] = 0


def _head(x_last, other, wte):
    if "lm_head.weight" in other:
        return x_last @ other["lm_head.weight"]
    return x_last @ wte.T


def _greedy(logits):
    """The repo's one greedy spelling (ops/decoding.greedy_decode /
    engine._sample_tok): argmax of f32 log_softmax."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.argmax(lp, axis=-1).astype(jnp.int32)


def make_draft_tick(cfg, num_slots: int, capacity: int, k: int,
                    feed_width: int, site: str):
    """Build the draft tick body (jitted by DraftRunner; caches
    donated).

    Args (all fixed-shape; one trace covers every scheduler state):
      stacked/other   draft decode params
      kc/vc           [L, ns, cap+1, NH, D] dense caches (pos ``cap``
                      is the trash column)
      feed_toks       [ns, F] catch-up tokens per slot
      feed_pos0       [ns]    first feed position per slot
      feed_len        [ns]    real feed tokens (0 = nothing to feed)
      gen_tok         [ns]    generation seed token (the slot's last
                              emitted/accepted token)
      gen_pos         [ns]    its position — ``cap`` for slots not
                              generating (their scan writes go to the
                              trash column and their drafts are
                              garbage the engine never offers)
      has_feed        bool    lax.cond fast path: steady-state ticks
                              skip the feed stage's compute entirely
      has_gen         bool    the symmetric fast path: feed-only ticks
                              (every chunked-prefill step) skip the
                              k-step generate scan — nobody would read
                              those drafts

    Returns (kc, vc, drafts [ns, k] — zeros when ``has_gen`` is off).
    """
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_eps
    msl = cfg.max_seq_len
    ns = num_slots
    cap = capacity
    f = feed_width

    from ..models.gpt import _ln, gpt_block_body

    def tick(stacked, other, kc, vc, feed_toks, feed_pos0, feed_len,
             gen_tok, gen_pos, has_feed, has_gen):
        _recompile.mark_trace(site, kc, feed_toks, gen_tok)
        wte = other["embeddings.wte.weight"]
        wpe = other["embeddings.wpe.weight"]
        rows = jnp.arange(ns)
        key_pos = jnp.arange(cap + 1)

        def feed(kc, vc):
            # chunk-style parallel catch-up: F tokens per slot in one
            # forward; pad positions (i >= feed_len) write to trash
            pos = feed_pos0[:, None] + jnp.arange(f)[None, :]  # [ns, F]
            real = jnp.arange(f)[None, :] < feed_len[:, None]
            wr = jnp.where(real, jnp.minimum(pos, cap), cap)
            x = wte[feed_toks] + wpe[jnp.clip(pos, 0, msl - 1)]

            def block(xc, inp):
                p, kc0, vc0 = inp

                def attend(q, kk, vv):
                    kcl = kc0.at[rows[:, None], wr].set(kk)
                    vcl = vc0.at[rows[:, None], wr].set(vv)
                    att = jnp.einsum("btnd,bsnd->bnts", q, kcl) / \
                        math.sqrt(hd)
                    mask = key_pos[None, None, None, :] <= \
                        pos[:, None, :, None]
                    att = jnp.where(mask, att, -1e9)
                    w = jax.nn.softmax(att.astype(jnp.float32),
                                       axis=-1).astype(xc.dtype)
                    return jnp.einsum("bnts,bsnd->btnd", w, vcl), \
                        (kcl, vcl)

                return gpt_block_body(xc, p, eps, nh, hd, attend)

            _, (kc, vc) = jax.lax.scan(block, x, (stacked, kc, vc))
            return kc, vc

        kc, vc = jax.lax.cond(has_feed, feed, lambda a, b: (a, b),
                              kc, vc)

        def gstep(carry, _):
            tok, kc, vc, p = carry
            wr = jnp.minimum(p, cap)
            x = wte[tok[:, None]] + wpe[jnp.clip(p, 0, msl - 1)][:, None]

            def block(xc, inp):
                pp, kc0, vc0 = inp

                def attend(q, kk, vv):
                    kcl = kc0.at[rows, wr].set(kk[:, 0])
                    vcl = vc0.at[rows, wr].set(vv[:, 0])
                    att = jnp.einsum("btnd,bsnd->bnts", q, kcl) / \
                        math.sqrt(hd)
                    mask = key_pos[None, None, None, :] <= \
                        p[:, None, None, None]
                    att = jnp.where(mask, att, -1e9)
                    w = jax.nn.softmax(att.astype(jnp.float32),
                                       axis=-1).astype(xc.dtype)
                    return jnp.einsum("bnts,bsnd->btnd", w, vcl), \
                        (kcl, vcl)

                return gpt_block_body(xc, pp, eps, nh, hd, attend)

            x, (kc, vc) = jax.lax.scan(block, x, (stacked, kc, vc))
            x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
            nxt = _greedy(_head(x[:, -1], other, wte))
            return (nxt, kc, vc, p + 1), nxt

        def generate(kc, vc):
            (_, kc, vc, _), drafts = jax.lax.scan(
                gstep, (gen_tok, kc, vc, gen_pos), None, length=k)
            return kc, vc, jnp.swapaxes(drafts, 0, 1)   # [ns, k]

        return jax.lax.cond(
            has_gen, generate,
            lambda kc, vc: (kc, vc, jnp.zeros((ns, k), jnp.int32)),
            kc, vc)

    return tick


def make_spec_tick(mcfg, num_slots: int, k: int, chunk_width: int,
                   impl: str, site: str, quantized: bool = False):
    """Build the spec engine's verify/mixed tick body (jitted by the
    engine; pools donated). This IS the unified mixed-row tick with a
    draft section — same site name, same single-trace contract.
    ``quantized`` (int8 KV pools, ISSUE 12) widens the signature with
    the per-page per-head scale arrays + the fresh-page reset vector,
    exactly like the plain unified tick; the draft model's dense cache
    stays at its own model dtype either way.

    Flat token layout: ``[ns last_tok | ns*k drafts | npf*w chunks]``.
    ``sample_ix`` is ``[ns * (1+k)]`` in that layout,
    ``reshape(ns, 1+k)``-able: column 0 is each slot's primary
    emission position (its last_tok row — or, for a slot whose final
    prefill chunk rides this tick, the chunk's last real position),
    columns 1..k its draft verify positions. ``n_draft`` [ns] is the
    per-slot speculation depth this tick (0 = plain decode row).

    Four branches, ONE executable (the decode-only fast-path idiom
    squared): with no drafts aboard the program runs the exact
    non-speculative graph (verify-row capacity costs nothing — the
    plain branches compute only the ns primary logits and scatter
    them into the fixed-shape output); with no chunks aboard the
    prefill capacity is skipped as before.

    Returns (kpool, vpool, tokens [ns, 1+k] — the target's greedy
    argmax at every verify position, accepted [ns]).
    """
    ns = num_slots
    w = chunk_width
    base = ns * (1 + k)

    from ..models.gpt import gpt_ragged_apply
    from ..ops.decoding import spec_accept_length

    def core(stacked, other, pools, last_tok, draft_toks,
             pf_toks, tok_pos, tok_limit, row_tab, row_pos0, row_len,
             sample_ix, n_draft, has_chunks, has_drafts):
        tokens = jnp.concatenate([last_tok, draft_toks, pf_toks])
        # the no-draft branches run the exact non-speculative layout:
        # the draft section sliced out of every metadata vector
        tokens_plain = jnp.concatenate([last_tok, pf_toks])
        pos_plain = jnp.concatenate([tok_pos[:ns], tok_pos[base:]])
        lim_plain = jnp.concatenate([tok_limit[:ns], tok_limit[base:]])
        # spec-layout sample indices remapped to the plain layout:
        # chunk-section indices shift down by the draft section; draft
        # indices (unused there — n_draft is all-zero whenever a plain
        # branch runs) clamp to 0
        is_draft = (sample_ix >= ns) & (sample_ix < base)
        ix_plain = jnp.where(
            sample_ix < ns, sample_ix,
            jnp.where(is_draft, 0, sample_ix - ns * k))
        primary_ix = ix_plain[jnp.arange(ns) * (1 + k)]

        def scatter_primary(tok_ns):
            # fixed-shape output: each slot's primary token lands at
            # its column-0 position; draft columns stay 0 (garbage the
            # host never reads when has_drafts is False)
            out = jnp.zeros((base,), jnp.int32)
            return out.at[jnp.arange(ns) * (1 + k)].set(tok_ns)

        def run(pl_, toks_, pos_, lim_, tab_, p0_, len_, six_, sk):
            if quantized:
                kp, vp, ks, vs = pl_
                lg, kp, vp, ks, vs = gpt_ragged_apply(
                    mcfg, stacked, other, kp, vp, toks_, pos_, lim_,
                    tab_, p0_, len_, six_, decode_rows=ns,
                    chunk_width=w, impl=impl, spec_k=sk,
                    kscale=ks, vscale=vs)
                return lg, (kp, vp, ks, vs)
            kp, vp = pl_
            lg, kp, vp = gpt_ragged_apply(
                mcfg, stacked, other, kp, vp, toks_, pos_, lim_,
                tab_, p0_, len_, six_, decode_rows=ns,
                chunk_width=w, impl=impl, spec_k=sk)
            return lg, (kp, vp)

        def spec_mixed(pl_):
            lg, pl_ = run(pl_, tokens, tok_pos, tok_limit, row_tab,
                          row_pos0, row_len, sample_ix, k)
            return (_greedy(lg),) + pl_

        def spec_only(pl_):
            lg, pl_ = run(pl_, tokens[:base], tok_pos[:base],
                          tok_limit[:base], row_tab[:ns], row_pos0[:ns],
                          row_len[:ns], sample_ix, k)
            return (_greedy(lg),) + pl_

        def plain_mixed(pl_):
            lg, pl_ = run(pl_, tokens_plain, pos_plain, lim_plain,
                          row_tab, row_pos0, row_len, primary_ix, 0)
            return (scatter_primary(_greedy(lg)),) + pl_

        def plain_only(pl_):
            lg, pl_ = run(pl_, tokens_plain[:ns], pos_plain[:ns],
                          lim_plain[:ns], row_tab[:ns], row_pos0[:ns],
                          row_len[:ns], primary_ix, 0)
            return (scatter_primary(_greedy(lg)),) + pl_

        out = jax.lax.cond(
            has_drafts,
            lambda pl_: jax.lax.cond(has_chunks, spec_mixed,
                                     spec_only, pl_),
            lambda pl_: jax.lax.cond(has_chunks, plain_mixed,
                                     plain_only, pl_),
            pools)
        toks, pools = out[0], out[1:]
        tok_m = toks.reshape(ns, 1 + k)
        acc = spec_accept_length(draft_toks.reshape(ns, k),
                                 tok_m[:, :k], n_draft)
        return pools, tok_m, acc

    if quantized:
        def tick(stacked, other, kpool, vpool, kscale, vscale, fresh,
                 last_tok, draft_toks, pf_toks, tok_pos, tok_limit,
                 row_tab, row_pos0, row_len, sample_ix, n_draft,
                 has_chunks, has_drafts):
            _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                  last_tok)
            # recycled pages start their running-max scale at 0 (the
            # engine lists pages allocated since the last dispatch)
            kscale = kscale.at[:, fresh].set(0.0)
            vscale = vscale.at[:, fresh].set(0.0)
            (kpool, vpool, kscale, vscale), tok_m, acc = core(
                stacked, other, (kpool, vpool, kscale, vscale),
                last_tok, draft_toks, pf_toks, tok_pos, tok_limit,
                row_tab, row_pos0, row_len, sample_ix, n_draft,
                has_chunks, has_drafts)
            return kpool, vpool, kscale, vscale, tok_m, acc
    else:
        def tick(stacked, other, kpool, vpool, last_tok, draft_toks,
                 pf_toks, tok_pos, tok_limit, row_tab, row_pos0,
                 row_len, sample_ix, n_draft, has_chunks, has_drafts):
            _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                  last_tok)
            (kpool, vpool), tok_m, acc = core(
                stacked, other, (kpool, vpool), last_tok, draft_toks,
                pf_toks, tok_pos, tok_limit, row_tab, row_pos0,
                row_len, sample_ix, n_draft, has_chunks, has_drafts)
            return kpool, vpool, tok_m, acc

    return tick
