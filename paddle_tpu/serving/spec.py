"""Speculative decoding on the paged serving engine (ROADMAP item 4).

Decode throughput on the unified tick is bounded by one target-model
dispatch per emitted token. Classic speculative decoding amortizes that
cost: a small **draft model** runs ``k`` tokens ahead per resident
slot, then ONE target **verify tick** scores every slot's
``k + 1``-token row through the existing mixed-row ragged program
(``models/gpt.py::gpt_ragged_apply`` with ``spec_k`` — a verify row is
exactly a prefill-chunk-shaped row whose logits are kept at every
position, not just the last).

**Greedy acceptance** takes the longest prefix where draft == target
argmax, plus one correction token; the emitted stream is therefore
always the TARGET's own argmax stream, so greedy speculative output is
**bitwise identical** to non-speculative greedy paged decode (which is
itself bitwise vs dense ``generate()``) — the classic invariant, and
this engine's signature parity-contract style (tests/test_spec_decode.py
pins it across admission orders, prefix-cache hits, COW divergence,
preemption/requeue mid-speculation, and exact-capacity finishes).

**Sampled acceptance** (ISSUE 20) is the rejection rule: accept draft
token ``t`` with probability ``min(1, p_tgt(t)/p_drf(t))``; on the
first rejection resample from the normalized residual
``max(0, p_tgt - p_drf)`` (``ops/decoding.spec_rejection_sample``).
Both distributions are filtered by the SAME per-request
temperature/top-k/top-p before the ratio — the draft tick filters its
own logits per row, the kernel filters the target's — so the marginal
law at every position is EXACTLY the non-speculative sampling law
(``engine._sample_tok``): ``categorical(fold_in(key, pos), lp)``. The
sampled analogue of greedy's bitwise pin is fixed-key stream equality
at both accept-rate extremes (twin draft → always accept → the
accepted token IS the non-spec draw; disjoint-support draft → always
reject → the residual equals ``p_tgt`` elementwise and the correction
IS the non-spec draw).

Two compiled dispatch sites, each tracing exactly once
(``ServingEngine.compiled_sites`` == {draft tick, verify/mixed tick}):

- **Draft tick** (``make_draft_tick``): the draft KV lives on the SAME
  ``PagePool`` allocator as the target (ISSUE 20 — the dense
  ``[L_d, ns, cap+1]`` buffer is gone): per-slot draft page tables
  (``paged_cache.AuxPageTable``) index draft-dtype pools
  ``[L_d, num_pages, page_size, NH_d, D_d]``, so draft and target
  bytes compete in one refcounted economy and the engine's pressure
  ladder can reclaim draft pages before preempting anyone. Pad and
  overflow writes route to page 0 (the null page — the paged analogue
  of the old dense trash column). One fixed-shape program does BOTH
  draft duties per scheduler step: a ``feed`` stage catches slots up
  to the target's accepted frontier, then a ``generate`` stage scans
  the draft steps; each stage sits behind its own ``lax.cond``.
  The sampling build additionally samples each draft token under the
  slot's own params/key (returning the filtered draft distributions
  the rejection kernel needs) and accepts a **chained frontier**: the
  previous verify tick's raw device outputs (``tok_m``, ``acc``) plus
  ``chain_mask``, from which it computes the post-absorb seed
  ``tok_m[s, acc]`` at position ``pos0 + acc + 1`` ON DEVICE — the
  engine dispatches this chained tick BEFORE materializing the verify
  result, hiding the per-tick host sync under the next draft tick's
  execution (the deferred-sync window spec mode used to forfeit). Its
  generate scan runs ``k + 1`` steps: step 0 re-writes the token at
  ``seed_pos - 1`` (heals the full-acceptance case, where draft ``k``
  was emitted but never written; for every other case it is an
  identical rewrite of an already-valid position, routed to the null
  page when not chained).
- **Verify tick** (``make_spec_tick``): the unified mixed-row tick
  widened with a draft section. Flat token layout
  ``[ns last_tok | ns*k drafts | chunks]``; slot rows group as
  ``[ns, 1+k]`` ragged rows (a non-speculating slot rides with
  ``row_len == 1``). Four ``lax.cond`` branches in ONE executable
  extend the decode-only fast path. The greedy build is unchanged;
  the sampling build threads per-request keys/params and the draft
  distributions, runs the rejection kernel in the spec branches and
  the plain per-row sampling law in the no-draft branches.

**Rewind** is what the PR-5 refcount/COW machinery makes safe: the
rejected tail's KV writes land in pages only this slot holds, so the
engine truncates ``pos`` and returns pages past the new length
(``shrink_slot`` on both the target tables and the draft's
``AuxPageTable``); the draft cache needs no repair — its own
speculation wrote the accepted tokens' KV, and the correction token
arrives as the next round's ``gen_tok`` (or the chained seed).
Preemption resets the slot's draft frontier to 0 and returns its draft
pages; the requeued prompt re-feeds chunk-by-chunk.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..profiler import recompile as _recompile
from .paged_cache import AuxPageTable

__all__ = ["SpecConfig", "DraftRunner", "make_draft_tick",
           "make_spec_tick"]


@dataclass
class SpecConfig:
    """Speculative-decoding knobs for ``ServingConfig.spec``.

    ``draft_model``: a dense ``GPT`` sharing the target's vocab (and
    ``max_seq_len >= target's``) — typically much smaller; quality only
    affects the accept rate, never correctness (rejected drafts cost a
    wasted verify position, accepted ones skip a target dispatch).
    ``k``: draft tokens speculated per verify tick; each slot's actual
    depth is clamped per tick by its remaining token budget and page
    headroom (down to 0 = a plain decode row).
    ``adaptive`` (ISSUE 15; serving/sched.py::SpecKController): drive
    each slot's depth from an accept-rate EWMA (alpha ``ewma_alpha``)
    instead of always offering the full ``k``.
    ``reprobe_every`` (ISSUE 16 satellite; ISSUE 20 makes it the BASE
    period): a slot stuck at depth 0 re-probes at depth 1, starting
    every this-many draft ticks and backing off multiplicatively on
    consecutive rejected probes (reset on an accepted one). 0 disables.
    ``overlap`` (ISSUE 20, sampling only): dispatch draft tick N+1
    against the pre-absorb frontier (chained on the verify tick's
    device outputs) BEFORE the host materializes the verify result —
    the per-tick sync hides under the next draft tick. Host-side
    reconcile falls back to a re-generate only when the slot's absorb
    diverged from the chain (EOS/finish/preemption)."""

    draft_model: object
    k: int = 4
    adaptive: bool = False
    ewma_alpha: float = 0.5
    reprobe_every: int = 64
    overlap: bool = False


class DraftRunner:
    """Draft-model state + the ONE jitted draft tick.

    Host side: ``len[s]`` is the slot's draft frontier (paged positions
    ``0..len[s]-1`` hold the accepted sequence's KV) and ``aux`` is the
    slot's draft page table on the SHARED pool allocator. Device side:
    the paged draft pools, donated per dispatch. The engine owns
    scheduling (what to feed, who generates) and frontier bookkeeping;
    this class owns the state and the compiled program."""

    def __init__(self, draft_model, num_slots: int, capacity: int,
                 k: int, feed_width: int, pool, sampling: bool = False):
        cfg = draft_model.config
        self.config = cfg
        self.k = int(k)
        self.capacity = int(capacity)
        self.feed_width = int(feed_width)
        self.sampling = bool(sampling)
        self.pool = pool
        self.aux = AuxPageTable(pool, num_slots)
        self.stacked, self.other = draft_model._decode_state()
        dt = self.other["embeddings.wte.weight"].dtype
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        ps = pool.page_size
        shape = (cfg.num_layers, pool.num_pages, ps, nh, hd)
        self.kc = jnp.zeros(shape, dt)
        self.vc = jnp.zeros(shape, dt)
        self.len = np.zeros(num_slots, np.int64)
        self.site = _recompile.unique_site("serving.draft")
        self.tick = jax.jit(
            make_draft_tick(cfg, num_slots, capacity, k, feed_width,
                            self.site, ps, sampling=sampling),
            donate_argnums=(2, 3))

    def held_tokens(self, slot: int) -> int:
        """Draft positions covered by the slot's held pages."""
        return self.aux.slot_pages(slot) * self.pool.page_size

    def grow_for(self, slot: int, n_tokens: int) -> bool:
        """Best-effort: hold enough draft pages for ``n_tokens``
        positions. False = pool couldn't cover it (the engine then
        clamps or skips speculation — draft growth never escalates)."""
        return self.aux.grow_to(slot, min(int(n_tokens), self.capacity))

    def rewind(self, slot: int, n_tokens: int) -> int:
        """Truncate the draft frontier to ``n_tokens`` and return pages
        past it to the pool (the rejection-rewind path). Returns pages
        freed."""
        self.len[slot] = int(n_tokens)
        return self.aux.shrink_slot(slot,
                                    self.pool.pages_for(int(n_tokens)))

    def release_pages(self, slot: int) -> int:
        """Pressure decay: return ALL of the slot's draft pages. The
        content is gone, so the frontier resets to 0 — a slot whose
        depth recovers re-feeds from scratch. Returns pages freed."""
        self.len[slot] = 0
        return self.aux.release_slot(slot)

    def reset_slot(self, slot: int) -> None:
        """Invalidate the slot's draft cache (admission / finish /
        preemption): frontier to 0, pages back to the pool."""
        self.len[slot] = 0
        self.aux.release_slot(slot)


def _head(x_last, other, wte):
    if "lm_head.weight" in other:
        return x_last @ other["lm_head.weight"]
    return x_last @ wte.T


def _greedy(logits):
    """The repo's one greedy spelling (ops/decoding.greedy_decode /
    engine._sample_tok): argmax of f32 log_softmax."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.argmax(lp, axis=-1).astype(jnp.int32)


def _sample_rows(logits, keys, pos, temps, top_ks, top_ps):
    """The engine's per-row sampling law (``engine._sample_tok``), on
    device: temperature → per-row top-k/top-p → log_softmax →
    ``categorical(fold_in(key, pos))``. Shared by the draft generate
    scan, the verify tick's plain branches, and (via the same ops) the
    rejection kernel — ONE spelling is what makes spec == non-spec."""
    from ..ops.decoding import apply_top_k_top_p_per_row

    lg = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    lg = apply_top_k_top_p_per_row(lg, top_ks, top_ps)
    lp = jax.nn.log_softmax(lg, axis=-1)
    def one(key, p, row):
        return jax.random.categorical(jax.random.fold_in(key, p), row)

    tok = jax.vmap(one)(keys, pos, lp).astype(jnp.int32)
    return tok, lp


def make_draft_tick(cfg, num_slots: int, capacity: int, k: int,
                    feed_width: int, site: str, page_size: int,
                    sampling: bool = False):
    """Build the draft tick body (jitted by DraftRunner; pools
    donated). The draft KV is PAGED (ISSUE 20): per-layer pools
    ``[num_pages, page_size, NH, D]`` indexed through the slot's draft
    page table ``dtab`` — position ``p`` of slot ``s`` lives at
    ``(dtab[s, p // ps], p % ps)``; pad/overflow writes route to the
    null page 0, and attention gathers the table view
    ``pool[dtab].reshape(ns, -1, NH, D)`` under the causal mask (null
    entries past the frontier are masked, contributing exactly 0).

    Greedy args (fixed-shape; one trace covers every scheduler state):
      stacked/other   draft decode params
      kc/vc           [L, num_pages, ps, NH, D] paged pools
      dtab            [ns, pages_per_slot] int32 draft page tables
      feed_toks       [ns, F] catch-up tokens per slot
      feed_pos0       [ns]    first feed position per slot
      feed_len        [ns]    real feed tokens (0 = nothing to feed)
      gen_tok         [ns]    generation seed token (the slot's last
                              emitted/accepted token)
      gen_pos         [ns]    its position — ``capacity`` for slots
                              not generating (their writes route to
                              the null page and their drafts are
                              garbage the engine never offers)
      has_feed        bool    lax.cond fast path: steady-state ticks
                              skip the feed stage's compute entirely
      has_gen         bool    the symmetric fast path: feed-only ticks
                              skip the generate scan

    Greedy returns (kc, vc, drafts [ns, k]).

    The sampling build inserts per-request ``keys [ns, 2] uint32``,
    ``temps``/``top_ks``/``top_ps`` [ns] and the chain args
    ``chain_tok_m [ns, 1+k]``, ``chain_acc [ns]``, ``chain_pos0 [ns]``,
    ``chain_mask [ns] bool`` after ``gen_pos``; chained rows override
    the seed with ``tok_m[s, acc]`` at ``pos0 + acc + 1`` on device
    (the overlap arm feeds the verify tick's un-materialized outputs
    straight in). Its generate scan runs ``k + 1`` steps — step 0
    re-writes position ``seed_pos - 1`` (the full-acceptance heal; an
    identical rewrite otherwise, null-routed when not chained) — and
    it returns (kc, vc, drafts [ns, k], dprobs [ns, k, V]) where
    ``dprobs`` are the FILTERED draft distributions the rejection
    kernel divides by.
    """
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_eps
    msl = cfg.max_seq_len
    vs = cfg.vocab_size
    ns = num_slots
    cap = capacity
    ps = page_size
    f = feed_width

    from ..models.gpt import _ln, gpt_block_body

    def body(stacked, other, kc, vc, dtab, feed_toks, feed_pos0,
             feed_len, gen_tok, gen_pos, has_feed, has_gen,
             sample_args):
        _recompile.mark_trace(site, kc, feed_toks, gen_tok)
        wte = other["embeddings.wte.weight"]
        wpe = other["embeddings.wpe.weight"]
        rows = jnp.arange(ns)
        slen = dtab.shape[1] * ps
        key_pos = jnp.arange(slen)

        def feed(kc, vc):
            # chunk-style parallel catch-up: F tokens per slot in one
            # forward; pad positions (i >= feed_len) write to the null
            # page
            pos = feed_pos0[:, None] + jnp.arange(f)[None, :]  # [ns, F]
            real = jnp.arange(f)[None, :] < feed_len[:, None]
            live = real & (pos >= 0) & (pos < cap)
            pc = jnp.clip(pos, 0, cap - 1)
            pg = jnp.where(live, dtab[rows[:, None], pc // ps], 0)
            off = pc % ps
            x = wte[feed_toks] + wpe[jnp.clip(pos, 0, msl - 1)]

            def block(xc, inp):
                p, kc0, vc0 = inp

                def attend(q, kk, vv):
                    kcl = kc0.at[pg, off].set(kk)
                    vcl = vc0.at[pg, off].set(vv)
                    kv = kcl[dtab].reshape(ns, slen, nh, hd)
                    vw = vcl[dtab].reshape(ns, slen, nh, hd)
                    att = jnp.einsum("btnd,bsnd->bnts", q, kv) / \
                        math.sqrt(hd)
                    mask = key_pos[None, None, None, :] <= \
                        pos[:, None, :, None]
                    att = jnp.where(mask, att, -1e9)
                    w = jax.nn.softmax(att.astype(jnp.float32),
                                       axis=-1).astype(xc.dtype)
                    return jnp.einsum("bnts,bsnd->btnd", w, vw), \
                        (kcl, vcl)

                return gpt_block_body(xc, p, eps, nh, hd, attend)

            _, (kc, vc) = jax.lax.scan(block, x, (stacked, kc, vc))
            return kc, vc

        kc, vc = jax.lax.cond(has_feed, feed, lambda a, b: (a, b),
                              kc, vc)

        if sampling:
            keys, temps, top_ks, top_ps, ch_tok_m, ch_acc, ch_pos0, \
                ch_mask = sample_args
            acc_c = jnp.clip(ch_acc, 0, k)
            g_tok = jnp.where(ch_mask, ch_tok_m[rows, acc_c], gen_tok)
            g_pos = jnp.where(ch_mask, ch_pos0 + acc_c + 1, gen_pos)
            # full-acceptance heal (step 0 of the scan): the token at
            # seed_pos - 1 — tok_m[acc - 1] for a chained row with
            # acc >= 1; rows with acc == 0 (and non-chained rows) have
            # that position valid already, so their step-0 write is
            # null-routed
            pre_mask = ch_mask & (ch_acc > 0)
            pre_tok = ch_tok_m[rows, jnp.clip(acc_c - 1, 0, k)]
        else:
            g_tok, g_pos = gen_tok, gen_pos
            pre_mask = jnp.zeros((ns,), bool)
            pre_tok = gen_tok
        scan_len = k + 1 if sampling else k

        def gstep(carry, i):
            tok, kc, vc, p = carry
            if sampling:
                # step 0 writes the heal token, step 1 is FORCED to the
                # seed (step 0's sampled output is not the true token
                # at the seed position), later steps chain as usual
                tok = jnp.where(i == 0, pre_tok,
                                jnp.where(i == 1, g_tok, tok))
                live = (p >= 0) & (p < cap) & \
                    jnp.where(i == 0, pre_mask, True)
            else:
                live = (p >= 0) & (p < cap)
            pc = jnp.clip(p, 0, cap - 1)
            pg = jnp.where(live, dtab[rows, pc // ps], 0)
            off = pc % ps
            x = wte[tok[:, None]] + wpe[jnp.clip(p, 0, msl - 1)][:, None]

            def block(xc, inp):
                pp, kc0, vc0 = inp

                def attend(q, kk, vv):
                    kcl = kc0.at[pg, off].set(kk[:, 0])
                    vcl = vc0.at[pg, off].set(vv[:, 0])
                    kv = kcl[dtab].reshape(ns, slen, nh, hd)
                    vw = vcl[dtab].reshape(ns, slen, nh, hd)
                    att = jnp.einsum("btnd,bsnd->bnts", q, kv) / \
                        math.sqrt(hd)
                    mask = key_pos[None, None, None, :] <= \
                        p[:, None, None, None]
                    att = jnp.where(mask, att, -1e9)
                    w = jax.nn.softmax(att.astype(jnp.float32),
                                       axis=-1).astype(xc.dtype)
                    return jnp.einsum("bnts,bsnd->btnd", w, vw), \
                        (kcl, vcl)

                return gpt_block_body(xc, pp, eps, nh, hd, attend)

            x, (kc, vc) = jax.lax.scan(block, x, (stacked, kc, vc))
            x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
            lg = _head(x[:, -1], other, wte)
            if sampling:
                # the token emitted after writing position p sits at
                # p + 1 — the same fold the plain tick uses there
                nxt, lp = _sample_rows(lg, keys, p + 1, temps,
                                       top_ks, top_ps)
                return (nxt, kc, vc, p + 1), (nxt, jnp.exp(lp))
            nxt = _greedy(lg)
            return (nxt, kc, vc, p + 1), nxt

        def generate(kc, vc):
            p0 = g_pos - 1 if sampling else g_pos
            (_, kc, vc, _), out = jax.lax.scan(
                gstep, (g_tok, kc, vc, p0),
                jnp.arange(scan_len), length=scan_len)
            if sampling:
                drafts, probs = out
                # step 0 is the heal write; drafts come from steps 1..k
                return (kc, vc, jnp.swapaxes(drafts[1:], 0, 1),
                        jnp.swapaxes(probs[1:], 0, 1))
            return kc, vc, jnp.swapaxes(out, 0, 1)   # [ns, k]

        def skip(kc, vc):
            if sampling:
                return (kc, vc, jnp.zeros((ns, k), jnp.int32),
                        jnp.zeros((ns, k, vs), jnp.float32))
            return kc, vc, jnp.zeros((ns, k), jnp.int32)

        return jax.lax.cond(has_gen, generate, skip, kc, vc)

    if sampling:
        def tick(stacked, other, kc, vc, dtab, feed_toks, feed_pos0,
                 feed_len, gen_tok, gen_pos, keys, temps, top_ks,
                 top_ps, chain_tok_m, chain_acc, chain_pos0,
                 chain_mask, has_feed, has_gen):
            return body(stacked, other, kc, vc, dtab, feed_toks,
                        feed_pos0, feed_len, gen_tok, gen_pos,
                        has_feed, has_gen,
                        (keys, temps, top_ks, top_ps, chain_tok_m,
                         chain_acc, chain_pos0, chain_mask))
    else:
        def tick(stacked, other, kc, vc, dtab, feed_toks, feed_pos0,
                 feed_len, gen_tok, gen_pos, has_feed, has_gen):
            return body(stacked, other, kc, vc, dtab, feed_toks,
                        feed_pos0, feed_len, gen_tok, gen_pos,
                        has_feed, has_gen, None)

    return tick


def make_spec_tick(mcfg, num_slots: int, k: int, chunk_width: int,
                   impl: str, site: str, quantized: bool = False,
                   sampling: bool = False):
    """Build the spec engine's verify/mixed tick body (jitted by the
    engine; pools donated). This IS the unified mixed-row tick with a
    draft section — same site name, same single-trace contract.
    ``quantized`` (int8 KV pools, ISSUE 12) widens the signature with
    the per-page per-head scale arrays + the fresh-page reset vector,
    exactly like the plain unified tick; the draft model's paged cache
    stays at its own model dtype either way.

    Flat token layout: ``[ns last_tok | ns*k drafts | npf*w chunks]``.
    ``sample_ix`` is ``[ns * (1+k)]`` in that layout,
    ``reshape(ns, 1+k)``-able: column 0 is each slot's primary
    emission position (its last_tok row — or, for a slot whose final
    prefill chunk rides this tick, the chunk's last real position),
    columns 1..k its draft verify positions. ``n_draft`` [ns] is the
    per-slot speculation depth this tick (0 = plain decode row).

    Four branches, ONE executable (the decode-only fast-path idiom
    squared): with no drafts aboard the program runs the exact
    non-speculative graph (verify-row capacity costs nothing — the
    plain branches compute only the ns primary logits and scatter
    them into the fixed-shape output); with no chunks aboard the
    prefill capacity is skipped as before.

    The greedy build (``sampling=False``) is unchanged from PR 9/15:
    returns (pools..., tokens [ns, 1+k] — the target's greedy argmax
    at every verify position, accepted [ns]). The sampling build adds
    ``keys [ns, 2] uint32``, ``sample_pos [ns]`` (column-0 emission
    positions), ``temps``/``top_ks``/``top_ps`` [ns] and
    ``draft_probs [ns, k, V]`` (the draft tick's filtered
    distributions); its spec branches run
    ``ops/decoding.spec_rejection_sample`` and its plain branches the
    per-row sampling law — acceptance must live INSIDE the branches
    there because it consumes the uniform draws.
    """
    ns = num_slots
    w = chunk_width
    base = ns * (1 + k)

    from ..models.gpt import gpt_ragged_apply
    from ..ops.decoding import spec_accept_length, spec_rejection_sample

    def core(stacked, other, pools, last_tok, draft_toks,
             pf_toks, tok_pos, tok_limit, row_tab, row_pos0, row_len,
             sample_ix, n_draft, has_chunks, has_drafts,
             sample_args=None):
        tokens = jnp.concatenate([last_tok, draft_toks, pf_toks])
        # the no-draft branches run the exact non-speculative layout:
        # the draft section sliced out of every metadata vector
        tokens_plain = jnp.concatenate([last_tok, pf_toks])
        pos_plain = jnp.concatenate([tok_pos[:ns], tok_pos[base:]])
        lim_plain = jnp.concatenate([tok_limit[:ns], tok_limit[base:]])
        # spec-layout sample indices remapped to the plain layout:
        # chunk-section indices shift down by the draft section; draft
        # indices (unused there — n_draft is all-zero whenever a plain
        # branch runs) clamp to 0
        is_draft = (sample_ix >= ns) & (sample_ix < base)
        ix_plain = jnp.where(
            sample_ix < ns, sample_ix,
            jnp.where(is_draft, 0, sample_ix - ns * k))
        primary_ix = ix_plain[jnp.arange(ns) * (1 + k)]

        def scatter_primary(tok_ns):
            # fixed-shape output: each slot's primary token lands at
            # its column-0 position; draft columns stay 0 (garbage the
            # host never reads when has_drafts is False)
            out = jnp.zeros((base,), jnp.int32)
            return out.at[jnp.arange(ns) * (1 + k)].set(tok_ns)

        def run(pl_, toks_, pos_, lim_, tab_, p0_, len_, six_, sk):
            if quantized:
                kp, vp, ks, vs = pl_
                lg, kp, vp, ks, vs = gpt_ragged_apply(
                    mcfg, stacked, other, kp, vp, toks_, pos_, lim_,
                    tab_, p0_, len_, six_, decode_rows=ns,
                    chunk_width=w, impl=impl, spec_k=sk,
                    kscale=ks, vscale=vs)
                return lg, (kp, vp, ks, vs)
            kp, vp = pl_
            lg, kp, vp = gpt_ragged_apply(
                mcfg, stacked, other, kp, vp, toks_, pos_, lim_,
                tab_, p0_, len_, six_, decode_rows=ns,
                chunk_width=w, impl=impl, spec_k=sk)
            return lg, (kp, vp)

        if sampling:
            keys, sample_pos, temps, top_ks, top_ps, draft_probs = \
                sample_args

            def accept(lg):
                tk, acc = spec_rejection_sample(
                    lg.reshape(ns, 1 + k, -1), draft_probs,
                    draft_toks.reshape(ns, k), n_draft, keys,
                    sample_pos, temps, top_ks, top_ps)
                return tk.reshape(base), acc

            def plain(lg):
                tok, _ = _sample_rows(lg, keys, sample_pos, temps,
                                      top_ks, top_ps)
                return scatter_primary(tok), jnp.zeros((ns,), jnp.int32)
        else:
            def accept(lg):
                # acceptance runs OUTSIDE the branches in greedy mode
                # (spec_accept_length is a pure token compare); keep
                # the branch contract uniform anyway
                return _greedy(lg), jnp.zeros((ns,), jnp.int32)

            def plain(lg):
                return scatter_primary(_greedy(lg)), \
                    jnp.zeros((ns,), jnp.int32)

        def spec_mixed(pl_):
            lg, pl_ = run(pl_, tokens, tok_pos, tok_limit, row_tab,
                          row_pos0, row_len, sample_ix, k)
            return accept(lg) + pl_

        def spec_only(pl_):
            lg, pl_ = run(pl_, tokens[:base], tok_pos[:base],
                          tok_limit[:base], row_tab[:ns], row_pos0[:ns],
                          row_len[:ns], sample_ix, k)
            return accept(lg) + pl_

        def plain_mixed(pl_):
            lg, pl_ = run(pl_, tokens_plain, pos_plain, lim_plain,
                          row_tab, row_pos0, row_len, primary_ix, 0)
            return plain(lg) + pl_

        def plain_only(pl_):
            lg, pl_ = run(pl_, tokens_plain[:ns], pos_plain[:ns],
                          lim_plain[:ns], row_tab[:ns], row_pos0[:ns],
                          row_len[:ns], primary_ix, 0)
            return plain(lg) + pl_

        out = jax.lax.cond(
            has_drafts,
            lambda pl_: jax.lax.cond(has_chunks, spec_mixed,
                                     spec_only, pl_),
            lambda pl_: jax.lax.cond(has_chunks, plain_mixed,
                                     plain_only, pl_),
            pools)
        toks, acc_b, pools = out[0], out[1], out[2:]
        tok_m = toks.reshape(ns, 1 + k)
        if sampling:
            acc = acc_b
        else:
            acc = spec_accept_length(draft_toks.reshape(ns, k),
                                     tok_m[:, :k], n_draft)
        return pools, tok_m, acc

    if sampling:
        if quantized:
            def tick(stacked, other, kpool, vpool, kscale, vscale,
                     fresh, last_tok, draft_toks, pf_toks, tok_pos,
                     tok_limit, row_tab, row_pos0, row_len, sample_ix,
                     n_draft, keys, sample_pos, temps, top_ks, top_ps,
                     draft_probs, has_chunks, has_drafts):
                _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                      last_tok)
                kscale = kscale.at[:, fresh].set(0.0)
                vscale = vscale.at[:, fresh].set(0.0)
                (kpool, vpool, kscale, vscale), tok_m, acc = core(
                    stacked, other, (kpool, vpool, kscale, vscale),
                    last_tok, draft_toks, pf_toks, tok_pos, tok_limit,
                    row_tab, row_pos0, row_len, sample_ix, n_draft,
                    has_chunks, has_drafts,
                    (keys, sample_pos, temps, top_ks, top_ps,
                     draft_probs))
                return kpool, vpool, kscale, vscale, tok_m, acc
        else:
            def tick(stacked, other, kpool, vpool, last_tok,
                     draft_toks, pf_toks, tok_pos, tok_limit, row_tab,
                     row_pos0, row_len, sample_ix, n_draft, keys,
                     sample_pos, temps, top_ks, top_ps, draft_probs,
                     has_chunks, has_drafts):
                _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                      last_tok)
                (kpool, vpool), tok_m, acc = core(
                    stacked, other, (kpool, vpool), last_tok,
                    draft_toks, pf_toks, tok_pos, tok_limit, row_tab,
                    row_pos0, row_len, sample_ix, n_draft, has_chunks,
                    has_drafts,
                    (keys, sample_pos, temps, top_ks, top_ps,
                     draft_probs))
                return kpool, vpool, tok_m, acc
    elif quantized:
        def tick(stacked, other, kpool, vpool, kscale, vscale, fresh,
                 last_tok, draft_toks, pf_toks, tok_pos, tok_limit,
                 row_tab, row_pos0, row_len, sample_ix, n_draft,
                 has_chunks, has_drafts):
            _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                  last_tok)
            # recycled pages start their running-max scale at 0 (the
            # engine lists pages allocated since the last dispatch)
            kscale = kscale.at[:, fresh].set(0.0)
            vscale = vscale.at[:, fresh].set(0.0)
            (kpool, vpool, kscale, vscale), tok_m, acc = core(
                stacked, other, (kpool, vpool, kscale, vscale),
                last_tok, draft_toks, pf_toks, tok_pos, tok_limit,
                row_tab, row_pos0, row_len, sample_ix, n_draft,
                has_chunks, has_drafts)
            return kpool, vpool, kscale, vscale, tok_m, acc
    else:
        def tick(stacked, other, kpool, vpool, last_tok, draft_toks,
                 pf_toks, tok_pos, tok_limit, row_tab, row_pos0,
                 row_len, sample_ix, n_draft, has_chunks, has_drafts):
            _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                  last_tok)
            (kpool, vpool), tok_m, acc = core(
                stacked, other, (kpool, vpool), last_tok, draft_toks,
                pf_toks, tok_pos, tok_limit, row_tab, row_pos0,
                row_len, sample_ix, n_draft, has_chunks, has_drafts)
            return kpool, vpool, tok_m, acc

    return tick
