"""paddle_tpu.serving — continuous-batching decode runtime on a paged
KV cache.

The serving-side answer to the ROADMAP's "heavy traffic from millions
of users": instead of one dense-cache ``generate()`` program per
request batch, a fixed pool of KV **pages** (``paged_cache.py``) plus a
fixed-shape jitted **decode tick** over cache slots (``engine.py``)
lets requests join and leave mid-decode — admission fills slots as
evictions free them, pages return to the pool the moment a request
finishes, and the host overlaps scheduling with device execution via
the PR-3 deferred-sync idiom. Attention over the paged layout lives in
``ops/paged_attention.py`` (XLA gather reference + gated Pallas
kernel).

Quick use::

    from paddle_tpu.serving import ServingEngine, ServingConfig
    eng = ServingEngine(gpt_model, ServingConfig(num_slots=8,
                                                 page_size=16))
    rids = [eng.submit(prompt, max_new_tokens=64) for prompt in prompts]
    outputs = eng.run()               # {rid: np.int32 ids}

or, per request batch with the familiar surface::

    ids, _ = gpt_model.generate(tokens, max_new_tokens=64, paged=True)

Profiler integration (``paddle_tpu.profiler``): gauges
``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util``, ``serving/tokens_per_sec``,
``serving/decode_batch``; counters ``serving/tokens_generated``,
``serving/prefills``, ``serving/ticks``, ``serving/preemptions``,
``serving/requests_finished``, ``serving/token_syncs``; histogram
``serving/ttft_ms``. Prefill length-bucket retraces are visible at the
``serving.prefill#N`` site in ``profiler.recompile`` telemetry; the
decode tick site must stay at ONE trace.
"""
from __future__ import annotations

from .engine import Request, ServingConfig, ServingEngine  # noqa: F401
from .paged_cache import NULL_PAGE, PageAllocator, PagePool  # noqa: F401

__all__ = ["ServingEngine", "ServingConfig", "Request",
           "PagePool", "PageAllocator", "NULL_PAGE"]
