"""paddle_tpu.serving — continuous-batching decode runtime on a paged
KV cache with prefix sharing.

The serving-side answer to the ROADMAP's "heavy traffic from millions
of users": instead of one dense-cache ``generate()`` program per
request batch, a fixed pool of KV **pages** (``paged_cache.py``) plus
ONE fixed-shape jitted **mixed-row tick** over cache slots
(``engine.py``) lets requests join and leave mid-decode — admission
fills slots as evictions free them, pages return to the pool the
moment their LAST holder lets go (the allocator refcounts pages), and
the host overlaps scheduling with device execution via the PR-3
deferred-sync idiom. Prompt prefixes are **shared**: fully-written
prompt pages live in a hash-trie index (``PrefixCache``) and admission
aliases the longest cached page-aligned prefix instead of recomputing
it; prefill of the remaining suffix is **chunked** (Sarathi-style —
bounded work per scheduler step) and the chunks ride the SAME tick as
resident decodes, as ragged rows of one
``ops/paged_attention.ragged_paged_attention`` call per layer
("Ragged Paged Attention": per-row ``(pos0, true_len)`` metadata; a
decode row is simply ``true_len == 1``). XLA gather spelling is the
measured default; a Pallas ragged kernel is interpret-verified and
gated for the real-TPU follow-up; ``attention_kernel="legacy"`` keeps
the pre-unification two-dispatch engine for benchmarking. Speculative
decoding (``ServingConfig.spec`` = ``SpecConfig(draft_model, k)``,
``spec.py``) amortizes the target over k drafted tokens per verify
tick with greedy acceptance — spec greedy output stays BITWISE equal
to plain greedy (the classic invariant, tested). Every POLICY
decision is pluggable and host-side (``sched.py``, ISSUE 15):
``ServingConfig.scheduler`` picks the chunk-selection order (fifo /
sjf / aged-sjf with a provable starvation bound), non-fifo policies
shape the per-tick prefill budget from decode-stall telemetry,
``SpecConfig.adaptive`` drives per-slot draft depth from an
accept-rate EWMA, and disagg routing balances on estimated
time-to-first-chunk — all without touching a compiled program.

Quick use::

    from paddle_tpu.serving import ServingEngine, ServingConfig
    eng = ServingEngine(gpt_model, ServingConfig(num_slots=8,
                                                 page_size=16))
    rids = [eng.submit(prompt, max_new_tokens=64) for prompt in prompts]
    outputs = eng.run()               # {rid: np.int32 ids}

or, per request batch with the familiar surface::

    ids, _ = gpt_model.generate(tokens, max_new_tokens=64, paged=True)

Profiler integration (``paddle_tpu.profiler``): gauges
``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util``, ``serving/tokens_per_sec``,
``serving/decode_batch``, ``serving/mixed_rows`` (+ ``_decode`` /
``_prefill`` split per tick); counters ``serving/tokens_generated``,
``serving/prefills``, ``serving/prefill_chunks``, ``serving/ticks``,
``serving/preemptions``, ``serving/requests_finished``,
``serving/token_syncs``, ``serving/prefix_lookups``,
``serving/prefix_hit_tokens``, ``cache_share/*`` (refcount traffic:
shares, releases, cow_copies, prefix_evictions); histograms
``serving/ttft_ms``, ``serving/prefill_queue_wait_ms``,
``serving/chunk_wait_ms`` (admission -> first chunk open); scheduler
policy (ISSUE 15, ``sched.py``) counters
``serving/aged_promotions``/``serving/budget_cuts`` and the
``serving/spec_k_effective`` gauge. The ONE
compiled hot-path site (``serving.tick#N``) must stay at ONE trace —
``ServingEngine.compiled_sites`` + the recompile registry make any
regression assertable (tests do).
"""
from __future__ import annotations

from .disagg import (DisaggServer, HandoffChannel, MeshSpec,  # noqa: F401
                     route_requests)
from .engine import Request, ServingConfig, ServingEngine  # noqa: F401
from .paged_cache import (NULL_PAGE, PageAllocator, PagePool,  # noqa: F401
                          PrefixCache)
from .sched import (SCHED_POLICIES, ChunkScheduler,  # noqa: F401
                    SpecKController)
from .spec import DraftRunner, SpecConfig  # noqa: F401

__all__ = ["ServingEngine", "ServingConfig", "Request", "SpecConfig",
           "DraftRunner", "PagePool", "PageAllocator", "PrefixCache",
           "NULL_PAGE", "DisaggServer", "MeshSpec", "HandoffChannel",
           "route_requests", "SCHED_POLICIES", "ChunkScheduler",
           "SpecKController"]
