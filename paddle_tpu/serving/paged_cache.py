"""Paged KV cache: refcounted page pool + free-list allocator + page
tables + prefix index.

The dense decode cache (``gpt_cached_apply``) charges every admitted
request ``S_max`` positions of HBM for its whole lifetime. Here the
cache is a pool of fixed-size pages shared by all slots; a request
holds ``ceil(len/page_size)`` pages and returns them at eviction, so
pool HBM tracks live tokens and a freed request's pages are reusable
immediately — the allocation granularity that makes continuous
batching admission-feasible mid-flight ("Ragged Paged Attention",
PAPERS.md).

Device state (``PagePool``): per-layer key/value pools stacked
``[L, num_pages, page_size, NH, D]``. One page id addresses the same
page row in every layer, so the allocator hands out a single id per
page regardless of depth.

Host state (``PageAllocator``): a LIFO free list over ids
``1..num_pages-1`` with a **refcount per allocated page**. ``alloc``
hands out pages at refcount 1; ``share`` lets a second holder (another
slot's page table, or the prefix index) alias the same page; ``free``
decrements and only returns the page to the free list at refcount 0.
**Page 0 is reserved as the null page**: inactive slots' table entries
point at it, decode-tick writes for inactive slots land in it, and
gathers through unallocated table entries read it (always masked).
LIFO reuse is deliberate — it maximizes the chance a test (or a bug)
sees a dirty page straight after free, which is exactly what the
no-cross-request-leakage test pins down.

Prefix index (``PrefixCache``): a hash-trie keyed on page-aligned
token chunks. A request's fully-written prompt pages are inserted as a
chain ``chunk -> page id``; admission walks the trie with the new
prompt and aliases every matched page instead of re-prefilling it.
Indexed pages are **immutable by construction** — writes only ever
target positions at or beyond the write frontier, and a page enters
the index only once the frontier has passed it — so sharing is safe
without copies, except for one case: a prompt that diverges from a
cached chunk mid-page can still reuse the agreeing positions by
**copy-on-write** (the engine copies the cached page into a fresh one
and overwrites from the divergence point). The index holds one
refcount per cached page; unreferenced cached pages (refcount 1, index
only) are evicted LRU leaf-first when the allocator runs dry.

Allocation, sharing and freeing are host-side bookkeeping only — no
device op; the tables are tiny int32 arrays shipped with each tick's
arguments.
"""
from __future__ import annotations

import hashlib
import heapq
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

NULL_PAGE = 0

#: hex chars kept per chunk-chain hash (blake2b); 16 hex chars = 64
#: bits — collision-safe for any realistic mesh index size, and short
#: enough that a whole digest rides a consensus vote as plain JSON.
CHAIN_HASH_LEN = 16


def chain_hash(parent_hash: str, chunk) -> str:
    """Stable hash of one page-aligned chunk IN ITS CHAIN CONTEXT:
    ``blake2b(parent_hash_bytes || chunk_token_bytes)``. Two ranks that
    cached the same prompt prefix compute the same chain of hashes
    (never Python ``hash()`` — that is salted per process), which is
    what lets the mesh index match prefixes by digest without ever
    shipping token bytes."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent_hash.encode("ascii"))
    h.update(np.asarray(list(chunk), np.int64).tobytes())
    return h.hexdigest()[:CHAIN_HASH_LEN]


def chain_hashes(tokens, page_size: int) -> List[str]:
    """Chunk-hash chain of every FULL page of ``tokens`` — the key a
    router uses to ask "which rank has the longest cached prefix of
    this prompt". Matches the hashes :class:`PrefixCache` stores on its
    trie nodes, by construction."""
    toks = np.asarray(tokens).reshape(-1)
    ps = int(page_size)
    out: List[str] = []
    parent = ""
    for i in range(toks.shape[0] // ps):
        parent = chain_hash(parent, toks[i * ps:(i + 1) * ps])
        out.append(parent)
    return out


def _registry():
    from ..profiler import registry

    return registry()


class PageAllocator:
    """LIFO free-list over page ids 1..num_pages-1 (0 is the null page)
    with per-page refcounts for prefix sharing."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # companion set: O(1) double-free detection (the list alone
        # would make release_slot O(pages_freed * free_list_len))
        self._free_set = set(self._free)
        self._ref: Dict[int, int] = {}       # allocated page -> refcount
        #: called with the list of pages whose LAST reference was just
        #: dropped (they are already back on the free list). The int8
        #: pool hooks this to queue a scale reset at free time instead
        #: of realloc time — a zero-freed page's stale running-max
        #: scale is scheduling history, not content (ISSUE 18).
        self.on_zero: Optional[Callable[[List[int]], None]] = None

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def utilization(self) -> float:
        """Allocated fraction of the allocatable pool (null page excluded)."""
        return self.num_allocated / max(self.num_pages - 1, 1)

    def refcount(self, page: int) -> int:
        """Current refcount of ``page`` (0 when free/never allocated)."""
        return self._ref.get(int(page), 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n page ids at refcount 1, or None (and no state change) if the
        pool can't cover the request — admission control needs
        all-or-nothing."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        for i in out:
            self._ref[i] = 1
        return out

    def share(self, ids) -> None:
        """Add one reference to each (already allocated) page — a second
        page table or the prefix index now aliases it."""
        shared = 0
        for i in ids:
            i = int(i)
            if i == NULL_PAGE:
                raise ValueError("page 0 (null page) is not shareable")
            if i not in self._ref:
                raise ValueError(f"share of unallocated page {i}")
            self._ref[i] += 1
            shared += 1
        if shared:
            _registry().counter("cache_share/shares").add(shared)

    def free(self, ids) -> None:
        """Drop one reference per page; a page returns to the free list
        only when its refcount reaches 0. Freeing an unallocated page
        raises (double-free of the LAST reference is a bug; releasing a
        still-shared page is the normal sharing path)."""
        released = 0
        zeroed: List[int] = []
        for i in ids:
            i = int(i)
            if i == NULL_PAGE:
                raise ValueError("page 0 (null page) is not allocatable")
            if i in self._free_set or i not in self._ref:
                raise ValueError(f"double free of page {i}")
            self._ref[i] -= 1
            if self._ref[i] == 0:
                del self._ref[i]
                self._free.append(i)
                self._free_set.add(i)
                zeroed.append(i)
            else:
                released += 1
        if released:
            _registry().counter("cache_share/releases").add(released)
        if zeroed and self.on_zero is not None:
            self.on_zero(zeroed)


class _TrieNode:
    __slots__ = ("chunk", "page", "children", "first_ix", "parent",
                 "last_use", "hash", "depth")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_TrieNode"]):
        self.chunk = chunk
        self.page = page
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        # chunk[0] -> child nodes: partial-match (COW) candidates. A
        # long-lived server accumulates one child per distinct suffix
        # under a shared-prompt node; scanning ALL of them per
        # admission would grow with history, while an LCP >= 1 match
        # must share the first token — so the common miss is one dict
        # probe.
        self.first_ix: Dict[int, List["_TrieNode"]] = {}
        self.parent = parent
        self.last_use = 0
        # chain hash + chain depth (root = depth 0): the digest the
        # mesh index publishes for this node (ISSUE 18)
        if parent is None:
            self.hash, self.depth = "", 0
        else:
            self.hash = chain_hash(parent.hash, chunk)
            self.depth = parent.depth + 1


class PrefixCache:
    """Hash-trie prefix index over page-aligned token chunks.

    Each node maps one ``page_size``-token chunk (in its parent's
    context) to the pool page holding that chunk's KV. The index owns
    one refcount per cached page; ``evict_for`` walks unreferenced
    leaves (refcount 1 — nobody but the index holds them) in LRU order
    when the allocator needs pages back. Lookup matches whole chunks
    along the trie, then optionally one **partial** chunk (longest
    common prefix against a child's tokens) for the engine's
    copy-on-write tail path.
    """

    def __init__(self, page_size: int, allocator: PageAllocator):
        self.page_size = int(page_size)
        self.allocator = allocator
        self._root = _TrieNode((), NULL_PAGE, None)
        self._clock = 0
        #: structural revision — bumps whenever the set of indexed
        #: chains changes (insert of a NEW node, any drop), so a
        #: publisher can skip recomputing/re-voting an unchanged
        #: digest on every heartbeat (ISSUE 18)
        self.rev = 0
        #: called as ``on_drop(chain_hash, n_tokens)`` when an indexed
        #: chain node is evicted, BEFORE its page goes back to the
        #: allocator — the hook a mesh-published rank uses to withdraw
        #: the digest from the board before the page is reclaimable
        #: (ISSUE 18: no routing to a stale digest).
        self.on_drop: Optional[Callable[[str, int], None]] = None

    def __len__(self) -> int:
        n, stack = 0, list(self._root.children.values())
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n

    def _touch(self, node: _TrieNode) -> None:
        self._clock += 1
        node.last_use = self._clock

    def lookup(self, tokens: np.ndarray):
        """Longest cached prefix of ``tokens``, capped at ``len - 1``
        (at least the last prompt position must be recomputed — its
        logits seed decoding).

        Returns ``(full_pages, partial)`` where ``full_pages`` is the
        page id per fully-matched chunk (in order) and ``partial`` is
        ``(page_id, lcp_len)`` for a chunk whose first ``lcp_len``
        tokens agree with the remainder (COW candidate), or None."""
        toks = np.asarray(tokens).reshape(-1)
        usable = toks.shape[0] - 1
        ps = self.page_size
        pages: List[int] = []
        node = self._root
        while (len(pages) + 1) * ps <= usable:
            key = tuple(int(t) for t in
                        toks[len(pages) * ps:(len(pages) + 1) * ps])
            nxt = node.children.get(key)
            if nxt is None:
                break
            node = nxt
            self._touch(node)
            pages.append(node.page)
        partial = None
        rem = usable - len(pages) * ps
        if rem > 0:
            rem_toks = toks[len(pages) * ps:len(pages) * ps + rem]
            best, best_child = 0, None
            for child in node.first_ix.get(int(rem_toks[0]), []):
                lcp = 0
                for a, b in zip(child.chunk, rem_toks):
                    if a != b:
                        break
                    lcp += 1
                if lcp > best:
                    best, best_child = lcp, child
                    if lcp == rem:
                        break
            if best_child is not None:
                self._touch(best_child)
                partial = (best_child.page, best)
        return pages, partial

    def insert(self, tokens: np.ndarray, pages) -> int:
        """Register ``pages[i]`` as holding the KV of chunk ``i`` of
        ``tokens`` (which must cover ``len(pages)`` full chunks). Pages
        already cached under the same chunk chain are left alone (the
        first tenant wins). Returns how many pages were newly indexed
        (each takes one index refcount)."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        if len(pages) * ps > toks.shape[0]:
            raise ValueError("insert needs one full chunk per page")
        parent = self._root
        new = 0
        for i, page in enumerate(pages):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            node = parent.children.get(key)
            if node is None:
                node = _TrieNode(key, int(page), parent)
                parent.children[key] = node
                parent.first_ix.setdefault(key[0], []).append(node)
                self.allocator.share([int(page)])
                new += 1
            self._touch(node)
            parent = node
        if new:
            self.rev += 1
        return new

    def _evictable_leaves(self) -> List[_TrieNode]:
        out, stack = [], list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.allocator.refcount(node.page) == 1:
                out.append(node)
        return out

    def _drop(self, node: _TrieNode) -> None:
        parent = node.parent
        del parent.children[node.chunk]
        bucket = parent.first_ix[node.chunk[0]]
        bucket.remove(node)
        if not bucket:
            del parent.first_ix[node.chunk[0]]
        # withdraw-before-reclaim: the hook must run while the index
        # still holds its reference — a router acting on the stale
        # digest one instant later must never find the page recycled
        # under it without the withdrawal having been recorded first
        if self.on_drop is not None:
            self.on_drop(node.hash, node.depth * self.page_size)
        self.rev += 1
        self.allocator.free([node.page])

    def evict_for(self, n: int) -> int:
        """Free up to ``n`` pages by evicting unreferenced cached pages,
        LRU leaf-first (evicting a mid-chain node would orphan its
        children's pages). One DFS collects the candidates; dropping a
        leaf can only newly expose its own parent, so the frontier is
        maintained incrementally instead of re-walking the trie per
        page. Returns how many pages were actually freed."""
        frontier = [(nd.last_use, id(nd), nd)
                    for nd in self._evictable_leaves()]
        heapq.heapify(frontier)
        freed = 0
        while freed < n and frontier:
            _, _, victim = heapq.heappop(frontier)
            parent = victim.parent
            self._drop(victim)
            freed += 1
            if parent is not self._root and not parent.children and \
                    self.allocator.refcount(parent.page) == 1:
                heapq.heappush(frontier,
                               (parent.last_use, id(parent), parent))
        if freed:
            _registry().counter("cache_share/prefix_evictions").add(freed)
        return freed

    def digest(self) -> Dict[str, object]:
        """JSON-able digest of every cached chain node: chunk-hash ->
        token count (``depth * page_size``). Digests — never token or
        page bytes — are what a rank publishes to the mesh index
        (ISSUE 18): small enough to ride a consensus vote, stable
        across processes, and sufficient for a router to compute the
        longest published prefix of any prompt via
        :func:`chain_hashes`."""
        chains: Dict[str, int] = {}
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            chains[node.hash] = node.depth * self.page_size
            stack.extend(node.children.values())
        return {"page_size": self.page_size, "chains": chains}

    def chain_pages(self, tokens) -> Tuple[List[int], List[str]]:
        """Walk the trie along the FULL chunks of ``tokens`` and return
        ``(pages, hashes)`` of the matched chain — the export side of
        hot-chain migration (no ``len - 1`` cap, no partial/COW leg:
        only whole indexed pages can be shipped). Touches the matched
        nodes (a migrating chain is hot by definition)."""
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        pages: List[int] = []
        hashes: List[str] = []
        node = self._root
        for i in range(toks.shape[0] // ps):
            key = tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
            nxt = node.children.get(key)
            if nxt is None:
                break
            node = nxt
            self._touch(node)
            pages.append(node.page)
            hashes.append(node.hash)
        return pages, hashes

    def pages(self) -> List[int]:
        """Every page id the index currently holds a refcount on (one
        per node) — the prefix leg of ``PagePool.check_consistency``."""
        out, stack = [], list(self._root.children.values())
        while stack:
            node = stack.pop()
            out.append(node.page)
            stack.extend(node.children.values())
        return out

    def clear(self) -> int:
        """Drop every index entry (still-shared pages lose only the
        index's refcount and survive in their slots). Returns the
        number of entries dropped."""
        dropped = 0
        stack = list(self._root.children.values())
        order: List[_TrieNode] = []
        while stack:
            node = stack.pop()
            order.append(node)
            stack.extend(node.children.values())
        for node in reversed(order):     # children before parents
            self._drop(node)
            dropped += 1
        return dropped


class PagePool:
    """Device page pools for all layers + host page tables for all slots."""

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, num_slots: int,
                 pages_per_slot: int, dtype=jnp.float32,
                 prefix_cache: bool = False):
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_slots = num_slots
        self.pages_per_slot = pages_per_slot
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # int8 pools (ISSUE 12): per-page per-head dequant scales ride
        # as device state next to the pools — quantize-on-write updates
        # them inside the tick (ops/paged_attention.paged_kv_scatter),
        # so they are donated/returned per dispatch exactly like k/v.
        # Page 0 (null) keeps scale 0 forever (masked contributions).
        # Page CONTENT is deliberately never cleared on free (LIFO
        # dirty reuse is a feature), but a recycled page's STALE SCALE
        # would poison the running-max of its next tenant — so fresh
        # allocations are tracked host-side and the engine folds a
        # scale reset for them into the next tick's arguments.
        self.quantized = jnp.dtype(dtype) == jnp.int8
        self.allocator = PageAllocator(num_pages)
        if self.quantized:
            self.k_scale = jnp.zeros((num_layers, num_pages, num_heads),
                                     jnp.float32)
            self.v_scale = jnp.zeros((num_layers, num_pages, num_heads),
                                     jnp.float32)
            self._fresh: List[int] = []
        self.allocator.on_zero = self._on_zero_free
        # pages that arrived via cross-rank chain migration (ISSUE 18):
        # host-side provenance so a prefix hit on one can be counted as
        # a REMOTE hit (the evidence the bench asserts on)
        self.migrated_pages: set = set()
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(page_size, self.allocator) if prefix_cache
            else None)
        # host copy of the per-slot page tables; rows of evicted slots
        # are zeroed (null page) so stale ids can never be gathered
        self.tables = np.zeros((num_slots, pages_per_slot), np.int32)
        # pages held per slot, in position order (prefix of the table row)
        self._held: List[List[int]] = [[] for _ in range(num_slots)]
        # auxiliary page tables (the spec-decode draft KV) drawing from
        # the SAME allocator: registered so check_consistency can
        # account for their holds (ISSUE 20)
        self._aux: List["AuxPageTable"] = []

    def register_aux(self, aux: "AuxPageTable") -> None:
        """Register an auxiliary table whose pages come from this
        pool's allocator — its holds join the consistency audit."""
        self._aux.append(aux)

    @property
    def slot_capacity(self) -> int:
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def slot_pages(self, slot: int) -> int:
        return len(self._held[slot])

    def _on_zero_free(self, pages: List[int]) -> None:
        """Allocator hook: runs when pages drop their LAST reference.
        ISSUE 18 quantizer fix — queue the int8 scale reset at free
        time, not at the next allocation: a page parked on the free
        list must not carry its old tenant's running-max scale as
        latent scheduling history (the PR 13 "tolerance-by-contract"
        residue). ``take_fresh``/``claim_fresh`` already dedupe, so
        re-listing a page the next ``_alloc`` will list again is
        harmless. Migration provenance ends with the last reference
        too: a recycled page id is not a migrated page."""
        if self.quantized:
            self._fresh.extend(pages)
        if self.migrated_pages:
            self.migrated_pages.difference_update(pages)

    def _alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages, evicting unreferenced prefix-cache
        pages LRU-first when the free list alone can't cover it."""
        got = self.allocator.alloc(n)
        if got is None and self.prefix is not None:
            self.prefix.evict_for(n - self.allocator.num_free)
            got = self.allocator.alloc(n)
        if got is not None and self.quantized:
            self._fresh.extend(got)
        return got

    # -- int8 scale lifecycle (quantized pools only) -------------------
    def take_fresh(self, cap: int) -> np.ndarray:
        """Drain the freshly-allocated-page list into a fixed-size
        int32 vector (padded with the null page, whose scale is 0
        anyway) for the next tick's in-program scale reset. Allocations
        beyond ``cap`` — which a correctly-sized cap never produces —
        are reset eagerly here instead of silently dropped (a dropped
        reset would leave a stale running-max scale on a recycled
        page)."""
        fresh, self._fresh = self._fresh, []
        if len(fresh) > cap:
            self.reset_scales(fresh[cap:])
            fresh = fresh[:cap]
        out = np.zeros(cap, np.int32)
        out[:len(fresh)] = fresh
        return out

    def reset_scales(self, pages) -> None:
        """Eagerly zero the scale rows of ``pages`` (rare overflow path
        of :meth:`take_fresh`; the hot path resets inside the tick)."""
        idx = np.asarray(list(pages), np.int32)
        if idx.size == 0:
            return
        self.k_scale = self.k_scale.at[:, idx].set(0.0)
        self.v_scale = self.v_scale.at[:, idx].set(0.0)

    def claim_fresh(self, page: int) -> None:
        """Remove ``page`` from the pending-reset list — its scale was
        just written by a device op (the COW copy duplicates the donor
        page's scale; resetting it afterwards would dequantize the
        copied content at scale 0). EVERY occurrence goes: an
        alloc→preempt-release→realloc cycle inside one scheduler step
        lists the same id twice, and a surviving duplicate would still
        zero the copied scales on the next tick."""
        if self.quantized:
            page = int(page)
            self._fresh = [p for p in self._fresh if p != page]

    def grow_slot(self, slot: int, n_pages: int) -> bool:
        """Extend ``slot`` by ``n_pages`` fresh pages; False (untouched)
        when the pool can't cover it."""
        if n_pages <= 0:
            return True
        held = self._held[slot]
        if len(held) + n_pages > self.pages_per_slot:
            raise ValueError(
                f"slot {slot} would exceed pages_per_slot="
                f"{self.pages_per_slot}")
        got = self._alloc(n_pages)
        if got is None:
            return False
        self.tables[slot, len(held):len(held) + n_pages] = got
        held.extend(got)
        return True

    def share_into_slot(self, slot: int, pages) -> None:
        """Alias already-allocated ``pages`` (a cached prefix) into the
        next table positions of ``slot``, taking one refcount each."""
        if not len(pages):
            return
        held = self._held[slot]
        if len(held) + len(pages) > self.pages_per_slot:
            raise ValueError(
                f"slot {slot} would exceed pages_per_slot="
                f"{self.pages_per_slot}")
        self.allocator.share(pages)
        self.tables[slot, len(held):len(held) + len(pages)] = \
            np.asarray(pages, np.int32)
        held.extend(int(p) for p in pages)

    def shrink_slot(self, slot: int, keep_pages: int) -> int:
        """Release the slot's pages BEYOND the first ``keep_pages``
        (position order — the speculative-rewind path: rejected draft
        tail tokens truncate the slot's frontier, and pages past the
        new length go back to the pool). Refcount-safe like
        ``release_slot``: only this slot's reference is dropped, so a
        page the prefix index (or another slot) still holds survives;
        the zeroed table tail means a stale id can never be gathered.
        No-op when the slot already holds ``<= keep_pages``. Returns
        how many page references were dropped."""
        if keep_pages < 0:
            raise ValueError("keep_pages must be >= 0")
        held = self._held[slot]
        drop = held[keep_pages:]
        if not drop:
            return 0
        self.allocator.free(drop)
        del held[keep_pages:]
        self.tables[slot, keep_pages:] = NULL_PAGE
        return len(drop)

    def release_slot(self, slot: int) -> int:
        """Drop ``slot``'s reference on all of its pages (a page only
        returns to the pool at refcount 0 — the prefix index or another
        slot may still hold it); zero the slot's table row. Idempotent:
        a second release of the same slot is a no-op (``_finish`` and
        preemption may both reach it), while over-freeing an individual
        page still raises inside the allocator. Returns how many page
        references were dropped."""
        held = self._held[slot]
        n = len(held)
        if n:
            self.allocator.free(held)
        self._held[slot] = []
        self.tables[slot, :] = NULL_PAGE
        return n

    def drop_prefix_cache(self) -> int:
        """Flush the prefix index (frees every unshared cached page);
        no-op without a prefix cache. Returns entries dropped."""
        return self.prefix.clear() if self.prefix is not None else 0

    def check_consistency(self) -> List[str]:
        """Audit the host-side invariants that every refcount edge —
        grow/share/COW/shrink/release, prefix insert/evict, and the
        ISSUE 13 export/import handoff path — must preserve. Returns a
        list of violation strings (empty = consistent); the multihost
        chaos tests assert a SURVIVOR's pool passes this after a peer
        died mid-handoff."""
        out = []
        holds: Dict[int, int] = {}
        for slot, held in enumerate(self._held):
            row = self.tables[slot]
            for i, pg in enumerate(held):
                holds[pg] = holds.get(pg, 0) + 1
                if int(row[i]) != pg:
                    out.append(f"slot {slot} table[{i}]={int(row[i])} "
                               f"!= held page {pg}")
            for i in range(len(held), self.pages_per_slot):
                if int(row[i]) != NULL_PAGE:
                    out.append(f"slot {slot} table[{i}]="
                               f"{int(row[i])} past the held prefix")
            if NULL_PAGE in held:
                out.append(f"slot {slot} holds the null page")
        if self.prefix is not None:
            for pg in self.prefix.pages():
                holds[pg] = holds.get(pg, 0) + 1
        for ax, aux in enumerate(self._aux):
            for slot, held in enumerate(aux._held):
                row = aux.tables[slot]
                for i, pg in enumerate(held):
                    holds[pg] = holds.get(pg, 0) + 1
                    if int(row[i]) != pg:
                        out.append(f"aux {ax} slot {slot} table[{i}]="
                                   f"{int(row[i])} != held page {pg}")
                for i in range(len(held), aux.pages_per_slot):
                    if int(row[i]) != NULL_PAGE:
                        out.append(f"aux {ax} slot {slot} table[{i}]="
                                   f"{int(row[i])} past the held prefix")
                if NULL_PAGE in held:
                    out.append(f"aux {ax} slot {slot} holds the null page")
        alloc = self.allocator
        for pg, want in holds.items():
            have = alloc.refcount(pg)
            if have != want:
                out.append(f"page {pg} refcount {have} != {want} "
                           "(table rows + prefix index)")
            if pg in alloc._free_set:
                out.append(f"page {pg} is held AND on the free list")
        for pg in alloc._ref:
            if pg not in holds:
                out.append(f"page {pg} allocated (refcount "
                           f"{alloc._ref[pg]}) but held by no slot or "
                           "index entry")
        n_booked = len(alloc._free) + len(alloc._ref)
        if n_booked != alloc.num_pages - 1:
            out.append(f"free ({len(alloc._free)}) + allocated "
                       f"({len(alloc._ref)}) != allocatable "
                       f"({alloc.num_pages - 1})")
        if set(alloc._free) != alloc._free_set:
            out.append("free list and free set disagree")
        return out


class AuxPageTable:
    """Per-slot page tables for an auxiliary KV cache (the spec-decode
    DRAFT model, ISSUE 20) drawing pages from the SAME allocator as the
    target pool — one id space, one refcount economy, one residency
    ledger, so draft and target bytes genuinely compete and the
    engine's page-pressure ladder can reclaim draft pages before
    resorting to preemption.

    Differences from the primary tables:
      * allocations are NOT fresh-listed — the draft cache is a
        separate f32 device array indexed by these tables, so the
        target pool's int8 scale rows for a draft-held page are never
        read; the allocator's ``on_zero`` hook still fresh-lists the
        page when its last reference drops, which is exactly when the
        TARGET pool could next gather it.
      * no sharing/COW/prefix legs: draft pages are private to their
        slot (refcount stays 1), and the rewind path is plain
        ``shrink_slot``.
    """

    def __init__(self, pool: PagePool, num_slots: int,
                 pages_per_slot: Optional[int] = None):
        self.pool = pool
        self.page_size = pool.page_size
        self.num_slots = int(num_slots)
        self.pages_per_slot = int(pages_per_slot
                                  if pages_per_slot is not None
                                  else pool.pages_per_slot)
        self.tables = np.zeros((num_slots, self.pages_per_slot), np.int32)
        self._held: List[List[int]] = [[] for _ in range(num_slots)]
        pool.register_aux(self)

    def slot_pages(self, slot: int) -> int:
        return len(self._held[slot])

    def total_pages(self) -> int:
        """Pages currently held across all slots — the draft-pool-share
        numerator in the serving gauges and bench cells."""
        return sum(len(h) for h in self._held)

    def grow_slot(self, slot: int, n_pages: int) -> bool:
        """Extend ``slot`` by ``n_pages`` pages from the shared
        allocator (evicting unreferenced prefix-cache pages if that is
        what it takes — same economy as the primary tables). False and
        untouched when the pool can't cover it: draft growth is
        BEST-EFFORT by design; the engine skips speculation rather
        than escalate for draft bytes."""
        if n_pages <= 0:
            return True
        held = self._held[slot]
        if len(held) + n_pages > self.pages_per_slot:
            raise ValueError(
                f"aux slot {slot} would exceed pages_per_slot="
                f"{self.pages_per_slot}")
        alloc = self.pool.allocator
        got = alloc.alloc(n_pages)
        if got is None and self.pool.prefix is not None:
            self.pool.prefix.evict_for(n_pages - alloc.num_free)
            got = alloc.alloc(n_pages)
        if got is None:
            return False
        self.tables[slot, len(held):len(held) + n_pages] = got
        held.extend(got)
        return True

    def grow_to(self, slot: int, n_tokens: int) -> bool:
        """Ensure ``slot`` holds enough pages for ``n_tokens`` draft
        positions (no-op when it already does)."""
        return self.grow_slot(
            slot, self.pool.pages_for(n_tokens) - len(self._held[slot]))

    def shrink_slot(self, slot: int, keep_pages: int) -> int:
        """Release pages beyond the first ``keep_pages`` (the
        rejection-rewind / pressure-decay path). Returns pages freed."""
        if keep_pages < 0:
            raise ValueError("keep_pages must be >= 0")
        held = self._held[slot]
        drop = held[keep_pages:]
        if not drop:
            return 0
        self.pool.allocator.free(drop)
        del held[keep_pages:]
        self.tables[slot, keep_pages:] = NULL_PAGE
        return len(drop)

    def release_slot(self, slot: int) -> int:
        """Return all of ``slot``'s draft pages to the pool; idempotent."""
        held = self._held[slot]
        n = len(held)
        if n:
            self.pool.allocator.free(held)
        self._held[slot] = []
        self.tables[slot, :] = NULL_PAGE
        return n
