"""Paged KV cache: fixed page pool + free-list allocator + page tables.

The dense decode cache (``gpt_cached_apply``) charges every admitted
request ``S_max`` positions of HBM for its whole lifetime. Here the
cache is a pool of fixed-size pages shared by all slots; a request
holds ``ceil(len/page_size)`` pages and returns them at eviction, so
pool HBM tracks live tokens and a freed request's pages are reusable
immediately — the allocation granularity that makes continuous
batching admission-feasible mid-flight ("Ragged Paged Attention",
PAPERS.md).

Device state (``PagePool``): per-layer key/value pools stacked
``[L, num_pages, page_size, NH, D]``. One page id addresses the same
page row in every layer, so the allocator hands out a single id per
page regardless of depth.

Host state (``PageAllocator``): a LIFO free list over ids
``1..num_pages-1``. **Page 0 is reserved as the null page**: inactive
slots' table entries point at it, decode-tick writes for inactive
slots land in it, and gathers through unallocated table entries read
it (always masked). LIFO reuse is deliberate — it maximizes the chance
a test (or a bug) sees a dirty page straight after free, which is
exactly what the no-cross-request-leakage test pins down.

Allocation and freeing are host-side bookkeeping only — no device op;
the tables are tiny int32 arrays shipped with each tick's arguments.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax.numpy as jnp

NULL_PAGE = 0


class PageAllocator:
    """LIFO free-list over page ids 1..num_pages-1 (0 is the null page)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # companion set: O(1) double-free detection (the list alone
        # would make release_slot O(pages_freed * free_list_len))
        self._free_set = set(self._free)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def utilization(self) -> float:
        """Allocated fraction of the allocatable pool (null page excluded)."""
        return self.num_allocated / max(self.num_pages - 1, 1)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n page ids, or None (and no state change) if the pool can't
        cover the request — admission control needs all-or-nothing."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, ids) -> None:
        for i in ids:
            i = int(i)
            if i == NULL_PAGE:
                raise ValueError("page 0 (null page) is not allocatable")
            if i in self._free_set:
                raise ValueError(f"double free of page {i}")
            self._free.append(i)
            self._free_set.add(i)


class PagePool:
    """Device page pools for all layers + host page tables for all slots."""

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, num_slots: int,
                 pages_per_slot: int, dtype=jnp.float32):
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_slots = num_slots
        self.pages_per_slot = pages_per_slot
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = PageAllocator(num_pages)
        # host copy of the per-slot page tables; rows of evicted slots
        # are zeroed (null page) so stale ids can never be gathered
        self.tables = np.zeros((num_slots, pages_per_slot), np.int32)
        # pages held per slot, in position order (prefix of the table row)
        self._held: List[List[int]] = [[] for _ in range(num_slots)]

    @property
    def slot_capacity(self) -> int:
        return self.pages_per_slot * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def slot_pages(self, slot: int) -> int:
        return len(self._held[slot])

    def grow_slot(self, slot: int, n_pages: int) -> bool:
        """Extend ``slot`` by ``n_pages`` pages; False (untouched) when
        the pool can't cover it."""
        if n_pages <= 0:
            return True
        held = self._held[slot]
        if len(held) + n_pages > self.pages_per_slot:
            raise ValueError(
                f"slot {slot} would exceed pages_per_slot="
                f"{self.pages_per_slot}")
        got = self.allocator.alloc(n_pages)
        if got is None:
            return False
        self.tables[slot, len(held):len(held) + n_pages] = got
        held.extend(got)
        return True

    def release_slot(self, slot: int) -> int:
        """Return all of ``slot``'s pages to the pool; zero its table
        row. Returns how many pages were freed."""
        held = self._held[slot]
        n = len(held)
        if n:
            self.allocator.free(held)
        self._held[slot] = []
        self.tables[slot, :] = NULL_PAGE
        return n
