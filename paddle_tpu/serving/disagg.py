"""Multi-host serving: sharded page pools, consensus-routed admission,
and prefill/decode disaggregation (ISSUE 13 tentpole piece 3).

Topology
--------
Each process (rank) of the mesh runs ONE local :class:`ServingEngine`
over its OWN page pool — the global KV pool is sharded by construction
(a page id is meaningful only on its owning rank; no cross-host page
table exists). Ranks are split into two slot groups:

- the **prefill group** (``MeshSpec.prefill_ranks``): long prompts are
  admitted here with ``hold_after_prefill`` — the engine runs the
  normal chunked/prefix-cached/preemptible prefill and samples the
  FIRST token, then the coordinator ships the finished KV pages to a
  decode rank through :class:`HandoffChannel` and releases the slot.
  A prefill engine's tick therefore only ever carries chunk rows.
- the **decode group** (everyone else): imports arrive decode-ready
  (``ServingEngine.admit_prefilled`` seeds the slot exactly where a
  local prefill finisher would have left it), so the decode tick takes
  its compiled decode-only ``lax.cond`` fast path whenever no local
  prefill is in flight — short prompts still prefill locally, long
  ones never touch this group's tick as chunk rows at all.

``MeshSpec(prefill_ranks=())`` is the **symmetric** scale-out
topology: every rank decodes its own admissions, no handoffs — the
1→N baseline the disaggregated split is measured against
(benchmarks/serve_bench.py --hosts N).

Admission (the consensus-routed part)
-------------------------------------
Every rank submits the SAME request stream in the same order (the SPMD
driver contract — global rids are just the submission sequence). Which
rank OWNS a request is decided by the :mod:`distributed.consensus`
primitive: each admission round, ranks vote their load (free pages,
free slots, queue depth) plus the highest global rid they have seen;
the leader reduces the votes with the pure routing function
(:func:`route_requests`) and publishes the assignment — every rank
then admits exactly its own requests, from its own copy of the stream.
No request data ever rides the vote; only loads and ids do. A rank
whose vote misses a round still adopts the published assignment, and a
dead rank is dropped from routing by lease expiry (its already-routed
requests die with it — re-dispatch of orphaned requests is residue,
ROADMAP).

KV handoff
----------
Pages transfer as raw pool bytes through an atomic-rename file channel
(the CPU test mesh's substrate; on a TPU fleet this hop is a
device-to-device ICI transfer and the channel is the seam to swap).
``kv_dtype="int8"`` pools hand off int8 values + per-page scales — the
PR 12 quantization prices the transfer at ~0.26x the f32 bytes
(``2*t0*NH*D`` int8 bytes + ``2*ceil(t0/ps)*NH`` f32 scale bytes per
layer vs ``8*t0*NH*D`` f32 bytes). A send is tmp-write + rename, so a
rank killed mid-handoff leaves only an ignorable ``.tmp`` — the
receiver's pool never sees a torn payload (chaos-tested in
tests/multihost/).

Cross-host tracing (ISSUE 14)
-----------------------------
Every request carries the deterministic trace id
``profiler.disttrace.trace_id(gid)`` — identical on every rank by the
SPMD driver contract — stamped as a ``trace`` attr on all of its
engine events and carried across the handoff, so the prefill rank's
and decode rank's event rings stitch into ONE timeline offline
(tools/merge_traces.py). The handoff payload gains a ``trace_ctx``
record (submit wall stamp, prefill-rank TTFT, export wall stamp), the
coordinator runs a Cristian-style clock sync against rank 0 on server
bring-up (``profiler.disttrace.ClockSync`` over ``<shared>/clock``;
the agreed offset table is published on the consensus board, family
``clock``, and mirrored into every rank's sink metadata), and a
handed-off request's TTFT is the TRUE end-to-end delta — prefill-rank
submit wall -> decode-rank first token, offset-corrected, ± the two
ranks' summed clock uncertainty (:meth:`DisaggServer.ttft_bounds`).
The old behavior (decode-side TTFT suppressed as a bogus ~0 ms pair,
``ttft_ms=None`` for every handed-off request) is gone.

Determinism: greedy disaggregated output is BITWISE the single-host
paged greedy stream (itself bitwise dense ``generate()``): the decode
rank attends over transferred page bytes identical to what its own
prefill would have written, per-token results are independent of which
rows share a program (``gpt_ragged_apply``'s contract), and sampling
keys ride the payload. tests/test_disagg.py pins this including
preemption on either side and int8 pools.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..distributed.consensus import Consensus
from ..profiler import disttrace as _disttrace
from ..profiler import events as _pevents
from ..profiler.metrics import registry as _registry
from .engine import ServingConfig, ServingEngine
from .sched import ttfc_key

__all__ = ["MeshSpec", "HandoffChannel", "DisaggServer",
           "route_requests"]


@dataclass(frozen=True)
class MeshSpec:
    """Who is who on the serving mesh. ``prefill_ranks=()`` means
    symmetric scale-out (every rank prefills + decodes its own
    admissions, no handoff)."""

    rank: int
    world: int
    prefill_ranks: Tuple[int, ...] = ()

    def __post_init__(self):
        if not 0 <= self.rank < self.world:
            raise ValueError(f"bad rank {self.rank}/{self.world}")
        bad = [r for r in self.prefill_ranks
               if not 0 <= r < self.world]
        if bad:
            raise ValueError(f"prefill ranks {bad} outside the mesh")
        if len(set(self.prefill_ranks)) == self.world:
            raise ValueError("every rank is a prefill rank: nobody "
                             "would decode")

    @property
    def decode_ranks(self) -> Tuple[int, ...]:
        return tuple(r for r in range(self.world)
                     if r not in self.prefill_ranks)

    @property
    def disaggregated(self) -> bool:
        return bool(self.prefill_ranks)

    @property
    def is_prefill(self) -> bool:
        return self.rank in self.prefill_ranks


class HandoffChannel:
    """Rank-to-rank KV payload transport over a shared directory.

    ``send`` is atomic (tmp write + rename): a reader either sees the
    whole payload or nothing — a sender killed mid-write leaves a
    ``.tmp`` nobody reads. ``poll`` consumes arrivals for THIS rank.
    ``pre_commit`` is the chaos seam: tests point it at
    ``mp_mesh.chaos_point`` to kill a rank between the payload bytes
    landing and the handoff becoming visible."""

    #: chaos hook, invoked between tmp-write and the atomic rename
    pre_commit = staticmethod(lambda: None)

    def __init__(self, directory: str, rank: int):
        self.dir = directory
        self.rank = int(rank)
        os.makedirs(directory, exist_ok=True)

    def send(self, dst: int, gid: int, payload: dict) -> int:
        """Ship ``payload`` to rank ``dst``; returns payload bytes."""
        final = os.path.join(self.dir, f"h-{gid:08d}-to{dst}.npz")
        tmp = final + f".tmp{os.getpid()}"
        arrays = {}
        for k, v in payload.items():
            arrays[k] = np.asarray(v)
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        HandoffChannel.pre_commit()
        os.rename(tmp, final)
        return sum(a.nbytes for a in arrays.values())

    def poll(self) -> List[Tuple[int, dict]]:
        """Consume every complete payload addressed to this rank."""
        out = []
        suffix = f"-to{self.rank}.npz"
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return out
        for n in names:
            if not (n.startswith("h-") and n.endswith(suffix)):
                continue
            path = os.path.join(self.dir, n)
            gid = int(n[2:10])
            try:
                with np.load(path) as z:
                    payload = {k: z[k] for k in z.files}
            except (OSError, ValueError):
                continue            # racing rename: next poll
            for k in ("orig_prompt_len", "max_new", "first_token",
                      "n_tokens", "preempts"):
                if k in payload:
                    payload[k] = int(payload[k])
            os.unlink(path)
            out.append((gid, payload))
        return out


def route_requests(votes: Dict[int, dict]) -> dict:
    """The admission reducer: a PURE function of one round's votes —
    whichever live rank leads publishes the same assignment.

    Each vote:  ``{"seen": hwm, "routed": n, "pending": {gid: plen},
    "free_pages": int, "free_slots": int, "queued": int,
    "prefill_backlog": tokens, "ttft_p95_ms": float, "chunk": int,
    "topology": {"prefill": [...], "decode": [...], "threshold": T}}``

    Routes every gid in ``[routed, min(seen over voters))``: a long
    prompt (``plen >= threshold``) goes to the best prefill rank (when
    a prefill group exists) and is decoded by the best decode rank;
    anything else is prefilled AND decoded by the best decode rank.
    "Best" is load-shaped (ISSUE 15; :func:`sched.ttfc_key`): the
    rank with the smallest estimated TIME-TO-FIRST-CHUNK — its
    queued-prefill-token backlog plus what this round already assigned
    it, in chunk-train units, a slot-overflow penalty, and the rank's
    rolling p95 TTFT as the measured tie-break — rather than free
    pages alone (free pages say nothing about how long a chunk train
    the new arrival queues behind, which is exactly the parked-shorts
    pathology BENCH_SERVE_r13 measured). Pre-ISSUE-15 votes (no
    backlog/p95 keys) degrade to a queue-depth estimate, so a
    mixed-version mesh still orders sanely. Deterministic tie-break
    toward the lower rank; same consensus round as before.
    """
    topo = votes[min(votes)]["topology"]
    prefill = list(topo["prefill"])
    decode = list(topo["decode"])
    threshold = int(topo["threshold"])
    routed = min(int(v["routed"]) for v in votes.values())
    upto = min(int(v["seen"]) for v in votes.values())
    lens: Dict[int, int] = {}
    for v in votes.values():
        for g, ln in v["pending"].items():
            lens[int(g)] = int(ln)

    # keyed by the TOPOLOGY's ranks, not the voters': a dead peer's
    # vote is missing but its rank is still routable (ttfc_key prices
    # it as busy — indexing it must not crash the leader)
    ranks_all = set(prefill) | set(decode)
    extra_tokens = {r: 0 for r in ranks_all}
    extra_reqs = {r: 0 for r in ranks_all}

    def pick(ranks):
        return min(ranks, key=lambda r: ttfc_key(
            votes, r, extra_tokens, extra_reqs))

    assign = {}
    for gid in range(routed, upto):
        plen = lens.get(gid)
        if plen is None:            # no voter carried it: leave queued
            break
        d = pick(decode)
        extra_reqs[d] += 1
        p = -1
        if prefill and plen >= threshold:
            p = pick(prefill)
            extra_reqs[p] += 1
            extra_tokens[p] += plen   # the chunk train runs HERE
        else:
            extra_tokens[d] += plen   # short prompts prefill where
        assign[str(gid)] = [p, d]     # they decode
    return {"assign": assign, "routed": routed + len(assign)}


def _clock_reducer(votes: Dict[int, dict]) -> dict:
    """The ``clock`` round's reducer: every rank's (offset, unc) vote,
    gathered into one table keyed by rank — pure and deterministic
    (votes arrive rank-sorted). The reference rank is taken from the
    lowest voter (every vote carries the same ``ref`` by
    construction)."""
    ref = int(votes[min(votes)].get("ref", 0))
    return {"ref": ref,
            "offsets": {str(r): {"offset_s": v.get("offset_s"),
                                 "unc_s": v.get("unc_s")}
                        for r, v in sorted(votes.items())}}


@dataclass
class _GlobalReq:
    gid: int
    prompt: np.ndarray
    max_new: int
    submit_w: float                  # wall clock (disttrace.walltime)
    trace: str = ""                  # deterministic cross-host trace id
    prefill_rank: int = -1
    decode_rank: int = -1
    routed: bool = False
    ttft_ms: Optional[float] = None
    #: ± clock-alignment uncertainty on ttft_ms — present exactly when
    #: ttft_ms is a CROSS-host delta corrected by a synced offset pair
    #: (same-host pairs have no cross-clock term; an unsynced mesh
    #: reports the delta with unc None = unbounded, never a fake 0)
    ttft_unc_ms: Optional[float] = None
    out: Optional[np.ndarray] = None
    meta: dict = field(default_factory=dict)


class DisaggServer:
    """One rank's serving coordinator on the mesh (module docstring).

    Driver contract: every rank constructs the same server over the
    same shared directory and calls ``submit`` with the SAME request
    stream in the same order; ``step()`` is the scheduler heartbeat
    (admission votes, exports, imports, one engine step); ``run()``
    drives until the mesh agrees the stream is fully served.

    ::

        mesh = MeshSpec(rank, world, prefill_ranks=(0,))
        srv = DisaggServer(model, cfg, mesh, shared_dir)
        for p in prompts:                 # identical on every rank
            srv.submit(p, max_new)
        srv.run()
        srv.results()                     # {gid: ids decoded HERE}
    """

    def __init__(self, model, config: ServingConfig, mesh: MeshSpec,
                 shared_dir: str, *,
                 long_prompt_threshold: Optional[int] = None,
                 consensus: Optional[Consensus] = None,
                 lease_s: float = 5.0,
                 clock_skew_s: Optional[float] = None,
                 clock_resync_s: float = 0.0):
        self.mesh = mesh
        self.engine = ServingEngine(model, config)
        self.consensus = consensus if consensus is not None else \
            Consensus(os.path.join(shared_dir, "board"), mesh.rank,
                      mesh.world, lease_s=lease_s)
        self.channel = HandoffChannel(
            os.path.join(shared_dir, "handoff"), mesh.rank)
        self.shared_dir = shared_dir
        #: prompts >= this many tokens route through the prefill group
        #: (default: one prefill chunk — anything longer would occupy
        #: multiple mixed ticks on a decode rank)
        self.long_prompt_threshold = (
            int(long_prompt_threshold) if long_prompt_threshold
            else self.engine.prefill_chunk + 1)
        self._reqs: Dict[int, _GlobalReq] = {}
        self._next_gid = 0
        self._routed_hwm = 0
        #: published assignments, kept keyed by gid: an assignment can
        #: ARRIVE before this rank's driver submitted the gid (a rank
        #: whose vote missed the window still gets routed to) — it is
        #: applied at submit() time instead of being dropped
        self._assignments: Dict[int, Tuple[int, int]] = {}
        self._served_total = 0
        self._voted_admit = False
        self._voted_done = False
        self._local: Dict[int, int] = {}      # local rid -> gid
        self._collected: set = set()
        self._pending_imports: List[Tuple[int, dict]] = []
        self.handoffs_sent = 0
        self.handoffs_recv = 0
        self._done_verdict: Optional[bool] = None
        self._done_open_t = 0.0
        # -- cross-host tracing (ISSUE 14) ------------------------------
        #: injected test skew applied to EVERY wall stamp this server
        #: makes (submit/export/import) AND to its clock-sync samples —
        #: one consistent wrong clock, exactly what a skewed host is.
        #: NOTE: the explicit ``clock_skew_s`` parameter skews only
        #: THIS server (in-process multi-server protocol tests, where
        #: a per-process sink could not represent two logical clocks
        #: anyway); a run whose per-rank sinks will be MERGED must
        #: inject skew via PADDLE_CLOCK_SKEW instead, which also
        #: reaches the sink's wall-clock anchor (disttrace.walltime)
        self._skew_s = _disttrace.local_skew_s(mesh.rank) \
            if clock_skew_s is None else float(clock_skew_s)
        self.clock = _disttrace.ClockSync(
            os.path.join(shared_dir, "clock"), mesh.rank, mesh.world,
            skew_s=self._skew_s)
        self._clock_voted = False
        #: the agreed offset table {str(rank): {offset_s, unc_s}}, or
        #: None until the ``clock`` consensus round publishes
        self._clock_table: Optional[Dict[str, dict]] = None
        #: periodic clock re-sync (ISSUE 15): every ``clock_resync_s``
        #: seconds after adoption, re-run the Cristian exchange on the
        #: heartbeat; when the fresh offset moved by MORE than its
        #: uncertainty, adopt it locally and re-vote the consensus
        #: ``clock`` round (a new epoch peers join via ``pending``, the
        #: straggler-heal machinery). 0 = one-shot sync (the PR 14
        #: behavior); the reference rank never resamples (its offset
        #: is 0 by definition) but keeps serving pongs either way.
        self.clock_resync_s = float(clock_resync_s)
        self._resyncing = False
        self._resync_at = float("inf")
        #: per-gid handoff trace context of IMPORTED requests:
        #: {gid: (ctx dict from the payload, import wall stamp)}
        self._handoff_ctx: Dict[int, Tuple[dict, float]] = {}
        # lease upkeep on a daemon thread: a rank COMPILING its first
        # tick (tens of seconds on a small box) is alive, and its lease
        # must say so or a fast peer transiently "survives" it and
        # decides rounds alone (Consensus.start_heartbeat docstring).
        self.consensus.start_heartbeat()

    def close(self) -> None:
        self.consensus.stop_heartbeat()

    def __enter__(self) -> "DisaggServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- submission (identical stream on every rank) -----------------------
    def submit(self, prompt_ids, max_new_tokens: int) -> int:
        p = np.asarray(prompt_ids, np.int32).reshape(-1)
        gid = self._next_gid
        self._next_gid += 1
        self._reqs[gid] = _GlobalReq(gid, p, int(max_new_tokens),
                                     self._walltime(),
                                     trace=_disttrace.trace_id(gid))
        # an open-ended driver (Poisson arrivals) may submit AFTER an
        # idle period already voted the mesh done — new work reopens
        # the question (the next done round sees served < seen)
        self._done_verdict = None
        if gid in self._assignments:
            # the mesh routed this gid before our driver submitted it
            # (our admission vote missed a round's window): apply the
            # published assignment now instead of orphaning it
            self._apply_assignment(gid)
        return gid

    # -- clock alignment (ISSUE 14) ----------------------------------------
    def _walltime(self) -> float:
        return _disttrace.walltime(self._skew_s)

    def _clock_round(self) -> None:
        """Non-blocking Cristian sync + consensus rounds: pump the
        ping exchange until this rank's estimate is ready, vote it
        (family ``clock``), adopt the published mesh-wide offset
        table. The reference rank keeps serving pongs forever (a
        cheap listdir on the heartbeat) so late peers can still
        sample. A rank the vote window expired OUT of the published
        table keeps sampling, self-heals its own entry the moment its
        estimate lands (its local stamps must not stay uncorrected),
        and re-votes — opening the NEXT clock epoch, which every peer
        joins via ``pending`` so the straggler's offset reaches the
        whole mesh; tables merge across epochs."""
        cons = self.consensus
        me = str(self.mesh.rank)
        healed = self._clock_table is not None and \
            me in self._clock_table
        if self.mesh.rank == self.clock.ref or not healed or \
                self._resyncing:
            self.clock.step()
        self._resync_round(me)
        if self._clock_table is not None and not healed and \
                self.clock.ready and not self._clock_voted:
            # window-expired straggler: heal locally NOW (peers may
            # already be draining), then gossip via the next epoch
            self._heal_local(self.clock.estimate())
            self._vote_clock()
        if self._clock_table is None:
            self._vote_clock()
        if self._clock_voted or cons.pending("clock"):
            # a pending round a peer opened (first sync OR a healed
            # straggler's re-round) is joined with our best estimate
            self._vote_clock()
            dec = cons.outcome("clock", reducer=_clock_reducer)
            if dec is not None:
                self._clock_voted = False
                self._adopt_clock(dec.value)

    def _heal_local(self, est: Tuple[float, float]) -> None:
        """Adopt a fresh LOCAL estimate into the table + the
        process clock state + the sink/event surfaces and re-derive
        collected TTFTs — the shared step of the straggler-heal and
        periodic-resync paths (a change to one must not silently miss
        the other; the caller follows with its own vote logic)."""
        self._clock_table[str(self.mesh.rank)] = {
            "offset_s": est[0], "unc_s": est[1]}
        _disttrace.set_clock_state(est[0], est[1], ref=self.clock.ref)
        _registry().gauge("consensus/clock_unc_ms").set(est[1] * 1e3)
        _pevents.emit("clock_sync", offset_s=est[0], unc_s=est[1],
                      ref=self.clock.ref)
        self._refresh_ttfts()

    def _resync_round(self, me: str) -> None:
        """Periodic drift tracking (ISSUE 15; retires the PR 14
        "one-shot sync, no drift tracking" residue): once the resync
        interval elapses, restart the ping exchange
        (``ClockSync.resync``) and pump it on the heartbeat; when the
        fresh estimate lands, compare it to the adopted entry — an
        offset that moved by MORE than the SUM of the two
        uncertainties is a real drift/step (two estimates each within
        ±unc of the truth can legitimately differ by up to
        unc_old + unc_new, so anything inside the summed bound is
        indistinguishable from measurement noise and must not churn
        epochs), so adopt it locally right away (our own stamps must
        not stay wrong while the round converges) and re-vote the
        ``clock`` family, opening a new epoch every peer joins via
        ``pending`` and adopts MERGED (the straggler-heal path's
        machinery, reused)."""
        if self.clock_resync_s <= 0 or self.mesh.rank == self.clock.ref:
            return
        if not self._resyncing:
            if self._clock_table is not None and me in \
                    self._clock_table and \
                    time.monotonic() >= self._resync_at:
                self.clock.resync()
                self._resyncing = True
            return
        if not self.clock.ready:
            return                    # still resampling
        self._resyncing = False
        self._resync_at = time.monotonic() + self.clock_resync_s
        est = self.clock.estimate()
        old = (self._clock_table or {}).get(me) or {}
        old_off = old.get("offset_s")
        bound = est[1] + float(old.get("unc_s") or 0.0)
        if old_off is not None and abs(est[0] - old_off) <= bound:
            return                    # within the stated uncertainty
        _registry().counter("consensus/clock_resyncs").add(1)
        self._heal_local(est)
        self._clock_voted = False
        self._vote_clock()

    def _vote_clock(self) -> None:
        """Cast this rank's clock vote in the current epoch, once,
        when its estimate exists (no-op otherwise)."""
        if self._clock_voted or not self.clock.ready:
            return
        est = self.clock.estimate()
        self.consensus.vote("clock", {"offset_s": est[0],
                                      "unc_s": est[1],
                                      "ref": self.clock.ref})
        self._clock_voted = True

    def _adopt_clock(self, value: dict) -> None:
        # MERGE across epochs: a straggler's re-round carries only
        # that epoch's voters — it must extend the table, not erase
        # the first round's entries
        table = dict(self._clock_table or {})
        table.update(value.get("offsets") or {})
        me = str(self.mesh.rank)
        if me not in table and self.clock.ready:
            # published without our vote (window expiry): our local
            # estimate still anchors our OWN sink metadata honestly
            est = self.clock.estimate()
            if est is not None:
                table[me] = {"offset_s": est[0], "unc_s": est[1]}
        self._clock_table = table
        mine = table.get(me)
        ref = int(value.get("ref", 0))
        off = None if mine is None else mine.get("offset_s")
        unc = None if mine is None else mine.get("unc_s")
        _disttrace.set_clock_state(off, unc, ref=ref,
                                   synced=mine is not None)
        if unc is not None:
            _registry().gauge("consensus/clock_unc_ms").set(unc * 1e3)
        _pevents.emit("clock_sync", offset_s=off, unc_s=unc, ref=ref)
        self._refresh_ttfts()
        if self.clock_resync_s > 0 and self._resync_at == float("inf"):
            # first adoption arms the periodic re-sync timer
            self._resync_at = time.monotonic() + self.clock_resync_s

    def _offset_of(self, rank: int) -> Tuple[float, Optional[float]]:
        """(offset_s, unc_s) of ``rank`` from the agreed table; an
        unsynced rank reads as offset 0 with unc None — uncorrected
        and explicitly unbounded, never silently exact."""
        e = (self._clock_table or {}).get(str(int(rank)))
        if e is None or e.get("offset_s") is None:
            return 0.0, None
        unc = e.get("unc_s")
        return float(e["offset_s"]), (None if unc is None
                                      else float(unc))

    # -- scheduling --------------------------------------------------------
    def _unrouted(self) -> List[int]:
        return [g for g in range(self._routed_hwm, self._next_gid)]

    def _admission_round(self) -> None:
        """Non-blocking consensus admission: vote when there is
        anything to route (or a peer opened the round), adopt the
        assignment when it publishes."""
        cons = self.consensus
        unrouted = self._unrouted()
        if not unrouted and not cons.pending("admit"):
            return
        if not self._voted_admit:
            eng = self.engine
            free_slots = sum(r is None for r in eng._slot_rid)
            # load-shaped vote (ISSUE 15): queued-prefill-token
            # backlog (every token a new arrival's first chunk waits
            # behind — queued prompts in full, residents' remaining
            # prefill) and the rank's rolling p95 TTFT, next to the
            # free-capacity counts the old reducer used alone
            backlog = sum(int(r.prompt.shape[0]) for r in eng._queue)
            for s, rid in enumerate(eng._slot_rid):
                if rid is not None:
                    backlog += max(0, int(eng._slot_prompt[s])
                                   - int(eng._slot_len[s]))
            # rolling p95 from the scheduler's bounded finish window
            # (O(64) — walking the profiler event ring here would put
            # an O(ring) scan on every admission round)
            p95 = eng._sched.ttft_p95()
            vote = {
                "seen": self._next_gid,
                "routed": self._routed_hwm,
                "pending": {str(g): int(self._reqs[g].prompt.shape[0])
                            for g in unrouted},
                "free_pages": int(eng.pool.allocator.num_free),
                "free_slots": int(free_slots),
                "queued": int(len(eng._queue)) + len(eng._held_ready),
                "prefill_backlog": int(backlog),
                "ttft_p95_ms": round(float(p95), 3),
                "chunk": int(eng.prefill_chunk),
                "page_size": int(eng.pool.page_size),
                "topology": {
                    "prefill": list(self.mesh.prefill_ranks),
                    "decode": list(self.mesh.decode_ranks),
                    "threshold": self.long_prompt_threshold,
                },
            }
            cons.vote("admit", vote)
            self._voted_admit = True
        dec = cons.outcome("admit", reducer=route_requests)
        if dec is None:
            return
        self._voted_admit = False
        assign = dec.value["assign"]
        if assign:
            _registry().counter("consensus/requests_routed") \
                .add(len(assign))
        for g_str, (p_rank, d_rank) in sorted(assign.items(),
                                              key=lambda kv: int(kv[0])):
            gid = int(g_str)
            self._assignments[gid] = (int(p_rank), int(d_rank))
            if int(d_rank) == self.mesh.rank:
                # the routing decision, as an event on the rank that
                # will OWN the visible result (one event per request
                # mesh-wide, not one per rank)
                _pevents.emit("route", gid=gid,
                              trace=_disttrace.trace_id(gid),
                              prefill=int(p_rank), decode=int(d_rank))
            if gid in self._reqs:
                self._apply_assignment(gid)
            # else: routed before our driver submitted it — submit()
            # applies the parked assignment when the gid arrives
        self._routed_hwm = max(self._routed_hwm,
                               int(dec.value["routed"]))

    def _apply_assignment(self, gid: int) -> None:
        req = self._reqs[gid]
        if req.routed:
            return
        req.prefill_rank, req.decode_rank = self._assignments[gid]
        req.routed = True
        me = self.mesh.rank
        if req.prefill_rank == me:
            lr = self.engine.submit(req.prompt, req.max_new,
                                    hold_after_prefill=True,
                                    trace_id=req.trace)
            self._local[lr] = gid
        elif req.decode_rank == me and req.prefill_rank < 0:
            lr = self.engine.submit(req.prompt, req.max_new,
                                    trace_id=req.trace)
            self._local[lr] = gid

    def _export_held(self) -> None:
        eng = self.engine
        for rid in eng.held_ready():
            gid = self._local.get(rid)
            if gid is None:          # not ours to ship (can't happen)
                continue
            req = self._reqs[gid]
            payload = eng.export_held(rid)
            # the prefill-rank leg of the trace rides the payload: the
            # decode rank (and the offline merger) need the submit
            # wall stamp to report a TRUE end-to-end TTFT instead of
            # the old suppressed decode-side ~0 ms pair. The engine's
            # same-host prefill TTFT (submit -> first token on THIS
            # rank) travels too — it is a clean clock pair and bounds
            # the handoff breakdown from the left.
            er = eng._requests[rid]
            prefill_ttft = None
            if er.first_token_t is not None:
                prefill_ttft = (er.first_token_t - er.submit_t) * 1e3
                req.meta["prefill_ttft_ms"] = prefill_ttft
            payload["trace_ctx"] = json.dumps({
                "trace": req.trace, "gid": gid,
                "prefill_rank": self.mesh.rank,
                "submit_w": req.submit_w,
                "export_w": self._walltime(),
                "prefill_ttft_ms": prefill_ttft,
            })
            self.channel.send(req.decode_rank, gid, payload)
            eng.release_exported(rid)
            self.handoffs_sent += 1

    def _import_arrivals(self) -> None:
        self._pending_imports.extend(self.channel.poll())
        still: List[Tuple[int, dict]] = []
        for gid, payload in self._pending_imports:
            lr = self.engine.admit_prefilled(payload)
            if lr is None:
                still.append((gid, payload))    # no slot/pages yet
                continue
            self._local[lr] = gid
            self.handoffs_recv += 1
            # stamp the import wall moment + keep the payload's trace
            # context: together with the agreed clock offsets they make
            # the handed-off request's end-to-end TTFT computable HERE
            # (keyed by gid, not _reqs — the import can land before our
            # driver submitted the gid)
            raw = payload.get("trace_ctx")
            if raw is not None:
                try:
                    ctx = json.loads(str(raw))
                except ValueError:   # pragma: no cover - torn context
                    ctx = None
                if ctx is not None:
                    self._handoff_ctx[gid] = (ctx, self._walltime())
                    # the channel-wait histogram sample is recorded in
                    # _stamp_e2e_ttft once the offsets are SYNCED — a
                    # histogram cannot retract a pre-adoption
                    # skew-corrupted observation the way ttft_ms can
                    # be re-derived
        self._pending_imports = still

    def _collect_finished(self) -> None:
        eng = self.engine
        # iterate OUR rid map, not the engine's whole request history:
        # the heartbeat must stay O(resident + uncollected), not
        # O(everything ever served)
        for rid, gid in list(self._local.items()):
            er = eng._requests.get(rid)
            if er is None or not er.done:
                continue
            if gid in self._collected:
                continue
            req = self._reqs[gid]
            if req.prefill_rank == self.mesh.rank and \
                    req.decode_rank != self.mesh.rank:
                continue            # done-by-export, not a result
            self._collected.add(gid)
            self._served_total += 1
            req.out = np.asarray(er.out, np.int32)
            # TTFT (ISSUE 14): a locally-served request keeps the
            # same-host engine clock pair; a handed-off one reports
            # the TRUE end-to-end delta — prefill-rank submit wall ->
            # this rank's import (its first-token moment), corrected
            # by the agreed clock offsets and carrying their summed
            # uncertainty. The old path suppressed the decode-side
            # pair entirely (first_token_t == submit_t at import — a
            # bogus ~0 ms) and left ttft_ms=None for every handed-off
            # request: the mesh's headline latency was unmeasurable by
            # construction.
            if req.ttft_ms is None and er.first_token_t is not None:
                if req.prefill_rank in (-1, self.mesh.rank):
                    req.ttft_ms = \
                        (er.first_token_t - er.submit_t) * 1e3
                    # the live plane's mesh TTFT sketch (ISSUE 16):
                    # the engine's own serving/ttft_ms is bogus-local
                    # for imported requests, so the coordinator owns
                    # an e2e histogram — one sample per gid, the same
                    # values write_results() reports
                    _registry().histogram(
                        "serving/e2e_ttft_ms").observe(req.ttft_ms)
                else:
                    self._stamp_e2e_ttft(req)
            req.meta["finish_w"] = self._walltime()

    def _stamp_e2e_ttft(self, req: _GlobalReq) -> None:
        """End-to-end TTFT of a request handed off TO this rank:
        (import wall - our offset) - (prefill-rank submit wall - its
        offset), in the reference rank's clock, ± the two offsets'
        summed uncertainty. A payload without a trace context (a
        pre-ISSUE-14 sender) leaves ttft_ms None — honestly absent,
        never the old bogus ~0 ms."""
        ctx, import_w = self._handoff_ctx.get(req.gid, (None, None))
        if ctx is None:
            return
        o_me, u_me = self._offset_of(self.mesh.rank)
        o_p, u_p = self._offset_of(int(ctx.get("prefill_rank", -1)))
        req.ttft_ms = ((import_w - o_me)
                       - (float(ctx["submit_w"]) - o_p)) * 1e3
        if u_me is not None and u_p is not None:
            first_stamp = req.ttft_unc_ms is None
            req.ttft_unc_ms = (u_me + u_p) * 1e3
            if first_stamp:
                # exactly one synced observation per handed-off
                # request (unc transitions None -> value once)
                _registry().histogram(
                    "serving/handoff_channel_wait_ms").observe(
                    ((import_w - o_me)
                     - (float(ctx["export_w"]) - o_p)) * 1e3)
                # same latch for the live plane's e2e TTFT sketch
                # (ISSUE 16): only the offset-corrected value lands —
                # a sketch cannot retract a skew-corrupted sample the
                # way _refresh_ttfts re-derives ttft_ms
                _registry().histogram(
                    "serving/e2e_ttft_ms").observe(req.ttft_ms)

    def _refresh_ttfts(self) -> None:
        """Re-derive handed-off TTFTs from their retained trace
        contexts under the CURRENT offset table: a request collected
        while the clock round was still converging (the mesh's first
        steps are compile-heavy — imports can beat adoption) was
        stamped uncorrected with unc None; once the table exists, the
        corrected value with its bound replaces it. Idempotent; called
        on every read surface (ttfts/ttft_bounds/write_results) and at
        table adoption."""
        if self._clock_table is None:
            return
        for gid in self._handoff_ctx:
            req = self._reqs.get(gid)
            if req is not None and req.ttft_ms is not None \
                    and req.ttft_unc_ms is None:
                self._stamp_e2e_ttft(req)

    def step(self) -> bool:
        """One coordinator heartbeat. Returns whether the local engine
        dispatched device work (the driver's idle signal)."""
        self.consensus.heartbeat()
        self._clock_round()
        self._admission_round()
        self._import_arrivals()
        progressed = self.engine.step()
        if not progressed and self.engine._inflight:
            self.engine.drain(0)
        self._export_held()
        self._collect_finished()
        self._done_round()
        return progressed

    def _clock_settled(self) -> bool:
        """The clock round is adopted — or can never be: a dead
        reference rank answers no pings and leads no round, so waiting
        on it would hold the whole drain hostage (TTFTs then ship
        uncorrected with unc None, which is the honest degraded
        outcome, not a hang)."""
        return self._clock_table is not None or \
            self.clock.ref not in self.consensus.alive()

    def quiescent(self) -> bool:
        """Locally drained: nothing unrouted, engine idle, no parked
        imports, no unexported holds — and the clock round settled (a
        short workload must not declare the mesh done while offsets
        are still converging: collected TTFTs would ship uncorrected.
        The round terminates on any live mesh: every stepping rank
        votes, a dead non-reference rank is window-expired by the
        leader, and a dead REFERENCE releases the gate outright —
        see :meth:`_clock_settled`)."""
        eng = self.engine
        return (self._clock_settled()
                and not self._unrouted()
                and not self._pending_imports
                and not eng._held_ready
                and not eng._queue and not eng._inflight
                and all(r is None for r in eng._slot_rid))

    def _done_round(self) -> None:
        """Non-blocking mesh-wide completion agreement: a ``done``
        vote round carries (idle, sent, recv, hwm) per rank; the mesh
        is done when every rank is idle with matching handoff ledgers.
        A QUIESCENT rank opens rounds (rate-limited); a BUSY rank joins
        any pending round immediately with ``idle=False`` — so no peer
        ever stalls on the vote window waiting for a rank that is
        simply working. Requires a healthy mesh: chaos tests drive
        ``step()`` + local quiescence instead (a corpse's ledger never
        balances — its unserved assignments are the documented
        residue)."""
        cons = self.consensus
        if self._voted_done:
            dec = cons.outcome("done", reducer=_done_reducer)
            if dec is not None:
                self._voted_done = False
                self._done_verdict = bool(dec.value)
            return
        q = self.quiescent()
        if cons.pending("done") or \
                (q and time.monotonic() - self._done_open_t > 0.2):
            cons.vote("done", {"idle": q,
                               "sent": self.handoffs_sent,
                               "recv": self.handoffs_recv,
                               "served": self._served_total,
                               "seen": self._next_gid,
                               "routed": self._routed_hwm})
            self._voted_done = True
            self._done_open_t = time.monotonic()

    def run(self, timeout_s: float = 600.0,
            poll_s: float = 0.005) -> Dict[int, np.ndarray]:
        """Drive until the mesh agrees the stream is served; returns
        the requests decoded on THIS rank ({gid: np.int32 ids})."""
        deadline = time.monotonic() + timeout_s
        while True:
            progressed = self.step()
            if self._done_verdict:
                break
            if not progressed:
                time.sleep(poll_s)      # waiting on arrivals or votes
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"disagg mesh did not drain: rank {self.mesh.rank} "
                    f"unrouted={len(self._unrouted())} "
                    f"held={len(self.engine._held_ready)} "
                    f"imports={len(self._pending_imports)} "
                    f"sent={self.handoffs_sent} recv={self.handoffs_recv}")
        return self.results()

    # -- results -----------------------------------------------------------
    def results(self) -> Dict[int, np.ndarray]:
        return {g: r.out for g, r in self._reqs.items()
                if r.out is not None}

    def reset_results(self) -> None:
        """Hand collected/forwarded requests back to the allocator: a
        long-running host must not grow ``_reqs``/``_local``/engine
        request history with every request ever served (the engine's
        ``reset_results`` idiom, lifted to the mesh level). Call after
        consuming ``results()``; mesh-wide done accounting survives
        (``_served_total`` is a monotonic counter, not a scan)."""
        drop_rids = []
        for rid, gid in self._local.items():
            er = self.engine._requests.get(rid)
            if er is None or not er.done:
                continue
            req = self._reqs.get(gid)
            exported = req is not None and \
                req.prefill_rank == self.mesh.rank and \
                req.decode_rank != self.mesh.rank
            if gid in self._collected or exported:
                drop_rids.append(rid)
        for rid in drop_rids:
            gid = self._local.pop(rid)
            self._reqs.pop(gid, None)
            self._collected.discard(gid)
            self._handoff_ctx.pop(gid, None)
        self.engine.reset_results()

    def ttfts(self) -> Dict[int, float]:
        """{gid: ttft_ms} owned by the rank that served the request's
        visible result: a same-host clock pair for locally-served
        requests, the offset-corrected END-TO-END delta (prefill-rank
        submit -> this rank's first token) for handed-off ones — see
        :meth:`ttft_bounds` for the uncertainty that delta carries."""
        self._refresh_ttfts()
        return {g: r.ttft_ms for g, r in self._reqs.items()
                if r.ttft_ms is not None}

    def ttft_uncs(self) -> Dict[int, float]:
        """{gid: ± clock-uncertainty ms} for the TTFTs that are
        cross-host deltas (the handed-off requests this rank decoded);
        same-host pairs and unsynced deltas are absent."""
        self._refresh_ttfts()
        return {g: r.ttft_unc_ms for g, r in self._reqs.items()
                if r.ttft_unc_ms is not None}

    def ttft_bounds(self) -> Dict[int, Tuple[float, float, float]]:
        """{gid: (lo_ms, ttft_ms, hi_ms)} — the TTFT with its clock-
        alignment error bar. Same-host pairs have no cross-clock term
        (lo == ttft == hi); a cross-host delta widens by the two
        ranks' summed offset uncertainty; a cross-host delta measured
        WITHOUT a synced clock table is excluded (its bounds would be
        fiction)."""
        self._refresh_ttfts()
        out = {}
        for g, r in self._reqs.items():
            if r.ttft_ms is None:
                continue
            handed = r.prefill_rank not in (-1, self.mesh.rank) and \
                r.decode_rank == self.mesh.rank
            if not handed:
                out[g] = (r.ttft_ms, r.ttft_ms, r.ttft_ms)
            elif r.ttft_unc_ms is not None:
                out[g] = (r.ttft_ms - r.ttft_unc_ms, r.ttft_ms,
                          r.ttft_ms + r.ttft_unc_ms)
        return out

    def write_results(self, path: str) -> None:
        """Atomic per-rank results artifact (the test/bench drivers
        merge these instead of adding a gather collective)."""
        self._refresh_ttfts()
        doc = {
            "rank": self.mesh.rank,
            "results": {str(g): r.out.tolist()
                        for g, r in self._reqs.items()
                        if r.out is not None},
            "ttft_ms": {str(g): round(t, 3)
                        for g, t in self.ttfts().items()},
            "ttft_unc_ms": {str(g): round(u, 3)
                            for g, u in self.ttft_uncs().items()},
            "clock": _disttrace.clock_state(),
            "handoffs_sent": self.handoffs_sent,
            "handoffs_recv": self.handoffs_recv,
        }
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def check_consistency(self) -> List[str]:
        """The local pool-shard audit (multihost chaos tests run this
        on SURVIVORS after a peer died mid-handoff)."""
        return self.engine.pool.check_consistency()


def _done_reducer(votes: Dict[int, dict]) -> bool:
    """Done iff every voter is idle, the handoff ledgers balance, every
    rank has seen+routed the same stream, AND every routed request was
    actually served (each gid finishes on exactly one rank, so served
    counts sum to the stream length). The served term is what makes a
    round decided while one rank's vote is transiently missing come out
    False instead of declaring victory over its unserved work."""
    idle = all(v["idle"] for v in votes.values())
    sent = sum(int(v["sent"]) for v in votes.values())
    recv = sum(int(v["recv"]) for v in votes.values())
    served = sum(int(v["served"]) for v in votes.values())
    seen = {int(v["seen"]) for v in votes.values()}
    routed = {int(v["routed"]) for v in votes.values()}
    return bool(idle and sent == recv and len(seen) == 1
                and routed == seen and served == seen.pop())
