"""Multi-host serving: sharded page pools, consensus-routed admission,
and prefill/decode disaggregation (ISSUE 13 tentpole piece 3).

Topology
--------
Each process (rank) of the mesh runs ONE local :class:`ServingEngine`
over its OWN page pool — the global KV pool is sharded by construction
(a page id is meaningful only on its owning rank; no cross-host page
table exists). Ranks are split into two slot groups:

- the **prefill group** (``MeshSpec.prefill_ranks``): long prompts are
  admitted here with ``hold_after_prefill`` — the engine runs the
  normal chunked/prefix-cached/preemptible prefill and samples the
  FIRST token, then the coordinator ships the finished KV pages to a
  decode rank through :class:`HandoffChannel` and releases the slot.
  A prefill engine's tick therefore only ever carries chunk rows.
- the **decode group** (everyone else): imports arrive decode-ready
  (``ServingEngine.admit_prefilled`` seeds the slot exactly where a
  local prefill finisher would have left it), so the decode tick takes
  its compiled decode-only ``lax.cond`` fast path whenever no local
  prefill is in flight — short prompts still prefill locally, long
  ones never touch this group's tick as chunk rows at all.

``MeshSpec(prefill_ranks=())`` is the **symmetric** scale-out
topology: every rank decodes its own admissions, no handoffs — the
1→N baseline the disaggregated split is measured against
(benchmarks/serve_bench.py --hosts N).

Admission (the consensus-routed part)
-------------------------------------
Every rank submits the SAME request stream in the same order (the SPMD
driver contract — global rids are just the submission sequence). Which
rank OWNS a request is decided by the :mod:`distributed.consensus`
primitive: each admission round, ranks vote their load (free pages,
free slots, queue depth) plus the highest global rid they have seen;
the leader reduces the votes with the pure routing function
(:func:`route_requests`) and publishes the assignment — every rank
then admits exactly its own requests, from its own copy of the stream.
No request data ever rides the vote; only loads and ids do. A rank
whose vote misses a round still adopts the published assignment, and a
dead rank is dropped from routing by lease expiry.

Elastic mesh (ISSUE 17)
-----------------------
Membership is no longer the static ``MeshSpec``: a consensus
``member`` family agrees on who is on the mesh, and routing topology,
done-agreement ledgers, clock participation, and the live plane all
follow the agreed member set.

- **dead-rank re-dispatch**: every rank holds every gid's prompt (the
  SPMD driver contract) and the published assignments, so when the
  mesh DECLARES a rank dead (its consensus lease stale past
  ``dead_after_s`` — the same lease evidence the PR 16 live plane
  corroborates with), survivors reconstruct its orphaned requests
  from their own route/ledger records and re-dispatch them through
  :func:`route_requests`. Re-prefill from the prompt is the honest
  fallback; a surviving exported-KV file addressed to the corpse is
  scavenged (atomic rename + payload audit) by a deterministic
  claimer instead of burning a fresh chunk train. The ``done``
  ledgers rebalance by VOIDING handoffs whose peer died
  (``sent - void_sent == recv - void_recv``), so the mesh still
  converges with zero lost requests.
- **dynamic membership**: a joiner announces itself by writing its
  consensus lease (``Consensus.alive`` discovers ranks from the
  board, not ``range(world)``), fast-forwards past pruned agreement
  history, and votes in a ``member`` round; the adopted decision
  carries the routing high-water mark so the joiner never re-routes
  already-assigned work.
- **live rebalancing**: a joiner (or a survivor inheriting a corpse's
  share) picks up queued and re-dispatched work through the existing
  load-shaped admission votes — the page-pool-pressure term in
  :func:`sched.ttfc_key` keeps the handoff sane.

Exactly-once honesty: the mesh guarantees every submitted request
FINISHES exactly once in the final converged ledger, but a request
whose owner died after serving it is re-served by a survivor — its
result is produced again (the corpse's in-memory copy is gone). A
consumer that already read a result from a rank that later died may
observe the re-serve; de-duplication by ``trace`` id is the
consumer's contract (README "Elastic serving mesh" table).

KV handoff
----------
Pages transfer as raw pool bytes through an atomic-rename file channel
(the CPU test mesh's substrate; on a TPU fleet this hop is a
device-to-device ICI transfer and the channel is the seam to swap).
``kv_dtype="int8"`` pools hand off int8 values + per-page scales — the
PR 12 quantization prices the transfer at ~0.26x the f32 bytes
(``2*t0*NH*D`` int8 bytes + ``2*ceil(t0/ps)*NH`` f32 scale bytes per
layer vs ``8*t0*NH*D`` f32 bytes). A send is tmp-write + rename, so a
rank killed mid-handoff leaves only an ignorable ``.tmp`` — the
receiver's pool never sees a torn payload (chaos-tested in
tests/multihost/).

Cross-host tracing (ISSUE 14)
-----------------------------
Every request carries the deterministic trace id
``profiler.disttrace.trace_id(gid)`` — identical on every rank by the
SPMD driver contract — stamped as a ``trace`` attr on all of its
engine events and carried across the handoff, so the prefill rank's
and decode rank's event rings stitch into ONE timeline offline
(tools/merge_traces.py). The handoff payload gains a ``trace_ctx``
record (submit wall stamp, prefill-rank TTFT, export wall stamp), the
coordinator runs a Cristian-style clock sync against rank 0 on server
bring-up (``profiler.disttrace.ClockSync`` over ``<shared>/clock``;
the agreed offset table is published on the consensus board, family
``clock``, and mirrored into every rank's sink metadata), and a
handed-off request's TTFT is the TRUE end-to-end delta — prefill-rank
submit wall -> decode-rank first token, offset-corrected, ± the two
ranks' summed clock uncertainty (:meth:`DisaggServer.ttft_bounds`).
The old behavior (decode-side TTFT suppressed as a bogus ~0 ms pair,
``ttft_ms=None`` for every handed-off request) is gone.

Determinism: greedy disaggregated output is BITWISE the single-host
paged greedy stream (itself bitwise dense ``generate()``): the decode
rank attends over transferred page bytes identical to what its own
prefill would have written, per-token results are independent of which
rows share a program (``gpt_ragged_apply``'s contract), and sampling
keys ride the payload. tests/test_disagg.py pins this including
preemption on either side and int8 pools.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..distributed.consensus import Consensus, lease_ages
from ..profiler import disttrace as _disttrace
from ..profiler import events as _pevents
from ..profiler.metrics import registry as _registry
from ..utils.retry import RetryError, retry as _retry
from .engine import ServingConfig, ServingEngine
from .paged_cache import chain_hashes
from .sched import prefix_affinity_key, ttfc_key

__all__ = ["MeshSpec", "HandoffChannel", "DisaggServer",
           "route_requests"]


@dataclass(frozen=True)
class MeshSpec:
    """Who is who on the serving mesh. ``prefill_ranks=()`` means
    symmetric scale-out (every rank prefills + decodes its own
    admissions, no handoff)."""

    rank: int
    world: int
    prefill_ranks: Tuple[int, ...] = ()

    def __post_init__(self):
        if not 0 <= self.rank < self.world:
            raise ValueError(f"bad rank {self.rank}/{self.world}")
        bad = [r for r in self.prefill_ranks
               if not 0 <= r < self.world]
        if bad:
            raise ValueError(f"prefill ranks {bad} outside the mesh")
        if len(set(self.prefill_ranks)) == self.world:
            raise ValueError("every rank is a prefill rank: nobody "
                             "would decode")

    @property
    def decode_ranks(self) -> Tuple[int, ...]:
        return tuple(r for r in range(self.world)
                     if r not in self.prefill_ranks)

    @property
    def disaggregated(self) -> bool:
        return bool(self.prefill_ranks)

    @property
    def is_prefill(self) -> bool:
        return self.rank in self.prefill_ranks


class HandoffChannel:
    """Rank-to-rank KV payload transport over a shared directory.

    ``send`` is atomic (tmp write + rename): a reader either sees the
    whole payload or nothing — a sender killed mid-write leaves a
    ``.tmp`` nobody reads. ``poll`` consumes arrivals for THIS rank.
    ``pre_commit`` is the chaos seam: tests point it at
    ``mp_mesh.chaos_point`` to kill a rank between the payload bytes
    landing and the handoff becoming visible.

    Transient I/O (ISSUE 17 satellite): every filesystem touch rides
    :func:`utils.retry.retry` exponential backoff against
    EINTR/ENOSPC-class ``OSError`` — a flaky shared dir must not look
    like a dead peer to the elastic mesh's death detector. Retries are
    counted into ``serving/handoff_retries``."""

    #: chaos hook, invoked between tmp-write and the atomic rename
    pre_commit = staticmethod(lambda: None)

    #: transient-I/O retry policy; class attributes so chaos tests can
    #: tighten the schedule without monkeypatching utils.retry
    retry_attempts = 4
    retry_base_delay_s = 0.01

    def __init__(self, directory: str, rank: int):
        self.dir = directory
        self.rank = int(rank)
        os.makedirs(directory, exist_ok=True)

    def _retry_io(self, fn):
        def _count(_i, _e, _d):
            _registry().counter("serving/handoff_retries").add(1)
        return _retry(fn, attempts=self.retry_attempts,
                      base_delay=self.retry_base_delay_s,
                      exceptions=(OSError,), on_retry=_count)

    def _path_to(self, gid: int, dst: int, kind: str = "h") -> str:
        return os.path.join(self.dir, f"{kind}-{gid:08d}-to{dst}.npz")

    def send(self, dst: int, gid: int, payload: dict,
             kind: str = "h") -> int:
        """Ship ``payload`` to rank ``dst``; returns payload bytes.
        ``kind`` prefixes the filename (default ``h`` = request
        handoff; ``m`` = prefix-chain migration, ISSUE 18) so the two
        payload families can never cross a poll: a migration chain
        imported as a request — or scavenged off a corpse as one —
        would be a torn admission."""
        final = self._path_to(gid, dst, kind)
        tmp = final + f".tmp{os.getpid()}"
        arrays = {}
        for k, v in payload.items():
            arrays[k] = np.asarray(v)

        def _write():
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)

        self._retry_io(_write)
        HandoffChannel.pre_commit()
        self._retry_io(lambda: os.rename(tmp, final))
        return sum(a.nbytes for a in arrays.values())

    def poll(self, kind: str = "h") -> List[Tuple[int, dict]]:
        """Consume every complete ``kind`` payload addressed to this
        rank."""
        out = []
        prefix = f"{kind}-"
        suffix = f"-to{self.rank}.npz"
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return out
        for n in names:
            if not (n.startswith(prefix) and n.endswith(suffix)):
                continue
            path = os.path.join(self.dir, n)
            gid = int(n[len(prefix):len(prefix) + 8])

            def _load(p=path):
                with np.load(p) as z:
                    return {k: z[k] for k in z.files}

            try:
                payload = self._retry_io(_load)
            except (RetryError, ValueError):
                continue            # racing rename / torn: next poll
            for k in ("orig_prompt_len", "max_new", "first_token",
                      "n_tokens", "preempts"):
                if k in payload:
                    payload[k] = int(payload[k])
            try:
                self._retry_io(lambda p=path: os.unlink(p))
            except RetryError:
                continue            # must not import without consuming
            out.append((gid, payload))
        return out

    def scavenge(self, gid: int, dead_rank: int) -> bool:
        """Claim a DEAD rank's unconsumed payload for this rank
        (ISSUE 17 re-dispatch): atomically rename
        ``h-<gid>-to<dead>.npz`` to address this rank, then audit that
        the payload actually loads with the keys an import needs — a
        torn or inconsistent file is deleted, not imported (the caller
        falls back to re-prefill, the honest path). Only safe once the
        mesh has DECLARED the addressee dead: a live addressee could
        race the rename with its own poll. Returns True when the
        payload is claimed and clean (the normal ``poll`` imports it
        next heartbeat)."""
        src = self._path_to(gid, dead_rank)
        dst = self._path_to(gid, self.rank)
        try:
            os.rename(src, dst)
        except OSError:
            if not os.path.exists(dst):   # nothing to claim
                return False
        try:
            with np.load(dst) as z:
                keys = set(z.files)
                need = {"prompt", "orig_prompt_len", "max_new",
                        "first_token", "key", "n_tokens", "kv_dtype",
                        "k", "v"}
                if not need <= keys:
                    raise ValueError(
                        f"payload missing {sorted(need - keys)}")
                if int(z["n_tokens"]) < 1 or \
                        z["k"].shape != z["v"].shape:
                    raise ValueError("inconsistent KV payload")
        except (OSError, ValueError, KeyError):
            try:
                os.unlink(dst)
            except OSError:
                pass
            _registry().counter(
                "serving/handoff_scavenge_failed").add(1)
            return False
        _registry().counter("serving/handoffs_scavenged").add(1)
        return True


def _chain_hit_tokens(chain: List[str], digest: dict) -> int:
    """Tokens of ``chain`` (a prompt's chunk-hash chain, lowest chunk
    first) covered by a rank's published ``digest`` — the longest
    UNBROKEN published prefix (a gap means the parent chain was
    evicted; anything past it is unusable)."""
    chains = digest.get("chains") or {}
    hit = 0
    for h in chain:
        n = chains.get(str(h))
        if n is None:
            break
        hit = int(n)
    return hit


def route_requests(votes: Dict[int, dict],
                   prefix_index: Optional[dict] = None) -> dict:
    """The admission reducer: a PURE function of one round's votes —
    whichever live rank leads publishes the same assignment.

    Each vote:  ``{"seen": hwm, "routed": n, "pending": {gid: plen},
    "free_pages": int, "free_slots": int, "queued": int,
    "prefill_backlog": tokens, "ttft_p95_ms": float, "chunk": int,
    "topology": {"prefill": [...], "decode": [...], "threshold": T}}``

    Routes every gid in ``[routed, min(seen over voters))``: a long
    prompt (``plen >= threshold``) goes to the best prefill rank (when
    a prefill group exists) and is decoded by the best decode rank;
    anything else is prefilled AND decoded by the best decode rank.
    "Best" is load-shaped (ISSUE 15; :func:`sched.ttfc_key`): the
    rank with the smallest estimated TIME-TO-FIRST-CHUNK — its
    queued-prefill-token backlog plus what this round already assigned
    it, in chunk-train units, a slot-overflow penalty, and the rank's
    rolling p95 TTFT as the measured tie-break — rather than free
    pages alone (free pages say nothing about how long a chunk train
    the new arrival queues behind, which is exactly the parked-shorts
    pathology BENCH_SERVE_r13 measured). Pre-ISSUE-15 votes (no
    backlog/p95 keys) degrade to a queue-depth estimate, so a
    mixed-version mesh still orders sanely. Deterministic tie-break
    toward the lower rank; same consensus round as before.

    Elastic extensions (ISSUE 17): the round's high-water mark is the
    MAX of the voters' (a joiner that fast-forwarded past pruned admit
    history votes a low hwm — every gid below the mesh's real mark was
    already assigned in decisions the lagging voter adopts in order,
    so re-routing them would double-serve); and a vote may carry a
    ``requeue`` list — gids whose assigned rank the mesh declared dead
    — which are re-routed through the same load-shaped pick, after
    the fresh range (their lens ride ``pending`` like any unrouted
    gid's).

    Global KV economy (ISSUE 18): when the caller passes the adopted
    mesh ``prefix_index`` ({rank: digest}) and votes carry per-gid
    chunk-hash ``chains``, the pick discounts each candidate by its
    published prefix coverage (:func:`sched.prefix_affinity_key` —
    hit length priced in the SAME chunk currency as the load terms,
    so a hot rank is not swamped by affinity). When the load vote
    still sends a request AWAY from its best published prefix by a
    page or more, the decision carries a ``migrate`` directive
    ``{gid: [src, dst]}`` — the owning rank replicates the hot chain
    to where the request will actually prefill. Pure policy: only the
    leader computes this; every peer ADOPTS the published decision,
    so a stale or rank-skewed index costs performance, never
    divergence.

    Membership fix (ISSUE 18 satellite): a rank the member round
    agreed OUT is excluded from every pick set — even when a stale
    vote of its still sits on the board — instead of being priced as
    merely busy. Votes without a ``members`` key (pre-ISSUE-18) keep
    the old price-as-busy behavior for missing voters.
    """
    members: Optional[set] = None
    for v in votes.values():
        m = v.get("members")
        if m is None:
            continue
        m = {int(r) for r in m}
        members = m if members is None else (members & m)
    if members:
        # an agreed-out rank's stale vote must not shape the round
        # either: casting a vote proves liveness, but a lingering
        # board file from before the eviction proves nothing
        live = {r: v for r, v in votes.items() if r in members}
        if live:
            votes = live
    topo = votes[min(votes)]["topology"]
    prefill = list(topo["prefill"])
    decode = list(topo["decode"])
    threshold = int(topo["threshold"])
    routed = max(int(v["routed"]) for v in votes.values())
    upto = min(int(v["seen"]) for v in votes.values())
    lens: Dict[int, int] = {}
    chains: Dict[int, List[str]] = {}
    for r in sorted(votes):
        for g, ln in votes[r]["pending"].items():
            lens[int(g)] = int(ln)
        for g, c in (votes[r].get("chains") or {}).items():
            chains.setdefault(int(g), [str(h) for h in c])

    # keyed by the TOPOLOGY's ranks, not the voters': a dead peer's
    # vote is missing but its rank is still routable (ttfc_key prices
    # it as busy — indexing it must not crash the leader) — UNLESS
    # the member round agreed it out
    if members is not None:
        prefill = [r for r in prefill if r in members]
        decode = [r for r in decode if r in members]
    ranks_all = set(prefill) | set(decode)
    extra_tokens = {r: 0 for r in ranks_all}
    extra_reqs = {r: 0 for r in ranks_all}

    def hits_for(gid):
        chain = chains.get(gid)
        if prefix_index is None or not chain:
            return None
        out = {}
        for r in ranks_all:
            dig = prefix_index.get(str(r)) or prefix_index.get(r)
            if dig:
                out[r] = _chain_hit_tokens(chain, dig)
        return out or None

    def pick(ranks, hits=None):
        if hits:
            return min(ranks, key=lambda r: prefix_affinity_key(
                votes, r, extra_tokens, extra_reqs, hits.get(r, 0)))
        return min(ranks, key=lambda r: ttfc_key(
            votes, r, extra_tokens, extra_reqs))

    def place(gid, plen, assign, migrate):
        if not decode:
            return False            # no routable decode rank: park
        hits = hits_for(gid)
        d = pick(decode, hits)
        extra_reqs[d] += 1
        p = -1
        if prefill and plen >= threshold:
            p = pick(prefill, hits)
            extra_reqs[p] += 1
            extra_tokens[p] += plen   # the chunk train runs HERE
        else:
            extra_tokens[d] += plen   # short prompts prefill where
        assign[str(gid)] = [p, d]     # they decode
        if hits:
            # the prefix pays off on the rank that RUNS the prefill;
            # when load pushed the request a page or more away from
            # its best published chain, direct the owner to replicate
            # the chain to the runner (hot-chain migration)
            runner = p if p >= 0 else d
            best = max(hits, key=lambda r: (hits[r], -r))
            ps = int((votes.get(best) or votes[min(votes)])
                     .get("page_size", 16))
            if best != runner and \
                    hits[best] - hits.get(runner, 0) >= ps:
                migrate[str(gid)] = [int(best), int(runner)]
        return True

    assign: Dict[str, List[int]] = {}
    migrate: Dict[str, List[int]] = {}
    fresh = 0
    for gid in range(routed, upto):
        plen = lens.get(gid)
        if plen is None:            # no voter carried it: leave queued
            break
        if not place(gid, plen, assign, migrate):
            break
        fresh += 1
    requeue = sorted({int(g) for v in votes.values()
                      for g in v.get("requeue", [])}
                     - {int(g) for g in assign})
    for gid in requeue:
        plen = lens.get(gid)
        if plen is None:
            continue                # no voter carries it any more
        place(gid, plen, assign, migrate)
    out = {"assign": assign, "routed": routed + fresh}
    if migrate:
        out["migrate"] = migrate
    return out


def _clock_reducer(votes: Dict[int, dict]) -> dict:
    """The ``clock`` round's reducer: every rank's (offset, unc) vote,
    gathered into one table keyed by rank — pure and deterministic
    (votes arrive rank-sorted). The reference rank is taken from the
    lowest voter (every vote carries the same ``ref`` by
    construction)."""
    ref = int(votes[min(votes)].get("ref", 0))
    return {"ref": ref,
            "offsets": {str(r): {"offset_s": v.get("offset_s"),
                                 "unc_s": v.get("unc_s")}
                        for r, v in sorted(votes.items())}}


def _member_reducer(votes: Dict[int, dict]) -> dict:
    """The ``member`` round's reducer (ISSUE 17): one agreed member
    set from the voters' views. Pure and deterministic:

    - the member table is the UNION of the voters' tables (iterated
      rank-sorted, first writer wins on role), plus every voter's own
      announcement (``me``/``role``) — that is how a joiner enters;
    - the dead set is the union of the voters' observations MINUS the
      voters themselves (casting a vote is proof of life — a rank can
      never be voted out of a round it is participating in), and dead
      ranks leave the member table;
    - ``routed`` is the MAX of the voters' admission high-water marks:
      the sync point a joiner adopts so it never re-routes work the
      mesh assigned before it arrived.
    """
    members: Dict[int, str] = {}
    for r in sorted(votes):
        v = votes[r]
        for k, role in sorted((v.get("members") or {}).items(),
                              key=lambda kv: int(kv[0])):
            members.setdefault(int(k), str(role))
        me = v.get("me")
        if me is not None:
            members.setdefault(int(me), str(v.get("role", "decode")))
    dead = set()
    for v in votes.values():
        dead.update(int(d) for d in v.get("dead", []))
    dead -= set(votes)
    for d in sorted(dead):
        members.pop(d, None)
    routed = max([int(v.get("routed", 0)) for v in votes.values()]
                 or [0])
    return {"members": {str(r): members[r] for r in sorted(members)},
            "dead": sorted(dead), "routed": routed}


def _prefix_reducer(votes: Dict[int, dict]) -> dict:
    """The ``prefix`` round's reducer (ISSUE 18): the mesh prefix
    index is simply every voter's digest keyed by rank — pure,
    deterministic (votes arrive rank-sorted), and tiny: chunk-hash
    chains with token lengths, NEVER page bytes or token ids. Adoption
    MERGES per rank across rounds (a round's voters may be a subset),
    and membership changes prune dead ranks' entries."""
    return {"index": {str(r): (v.get("digest") or {})
                      for r, v in sorted(votes.items())}}


@dataclass
class _GlobalReq:
    gid: int
    prompt: np.ndarray
    max_new: int
    submit_w: float                  # wall clock (disttrace.walltime)
    trace: str = ""                  # deterministic cross-host trace id
    prefill_rank: int = -1
    decode_rank: int = -1
    routed: bool = False
    ttft_ms: Optional[float] = None
    #: ± clock-alignment uncertainty on ttft_ms — present exactly when
    #: ttft_ms is a CROSS-host delta corrected by a synced offset pair
    #: (same-host pairs have no cross-clock term; an unsynced mesh
    #: reports the delta with unc None = unbounded, never a fake 0)
    ttft_unc_ms: Optional[float] = None
    out: Optional[np.ndarray] = None
    meta: dict = field(default_factory=dict)


class DisaggServer:
    """One rank's serving coordinator on the mesh (module docstring).

    Driver contract: every rank constructs the same server over the
    same shared directory and calls ``submit`` with the SAME request
    stream in the same order; ``step()`` is the scheduler heartbeat
    (admission votes, exports, imports, one engine step); ``run()``
    drives until the mesh agrees the stream is fully served.

    ::

        mesh = MeshSpec(rank, world, prefill_ranks=(0,))
        srv = DisaggServer(model, cfg, mesh, shared_dir)
        for p in prompts:                 # identical on every rank
            srv.submit(p, max_new)
        srv.run()
        srv.results()                     # {gid: ids decoded HERE}
    """

    def __init__(self, model, config: ServingConfig, mesh: MeshSpec,
                 shared_dir: str, *,
                 long_prompt_threshold: Optional[int] = None,
                 consensus: Optional[Consensus] = None,
                 lease_s: float = 5.0,
                 dead_after_s: Optional[float] = None,
                 join: bool = False,
                 clock_skew_s: Optional[float] = None,
                 clock_resync_s: float = 0.0,
                 prefix_routing: bool = False,
                 prefix_publish_s: float = 0.5):
        self.mesh = mesh
        self.engine = ServingEngine(model, config)
        self.consensus = consensus if consensus is not None else \
            Consensus(os.path.join(shared_dir, "board"), mesh.rank,
                      mesh.world, lease_s=lease_s)
        self.channel = HandoffChannel(
            os.path.join(shared_dir, "handoff"), mesh.rank)
        self.shared_dir = shared_dir
        #: prompts >= this many tokens route through the prefill group
        #: (default: one prefill chunk — anything longer would occupy
        #: multiple mixed ticks on a decode rank)
        self.long_prompt_threshold = (
            int(long_prompt_threshold) if long_prompt_threshold
            else self.engine.prefill_chunk + 1)
        self._reqs: Dict[int, _GlobalReq] = {}
        self._next_gid = 0
        self._routed_hwm = 0
        #: published assignments, kept keyed by gid: an assignment can
        #: ARRIVE before this rank's driver submitted the gid (a rank
        #: whose vote missed the window still gets routed to) — it is
        #: applied at submit() time instead of being dropped
        self._assignments: Dict[int, Tuple[int, int]] = {}
        self._served_total = 0
        self._voted_admit = False
        self._voted_done = False
        self._local: Dict[int, int] = {}      # local rid -> gid
        self._collected: set = set()
        self._pending_imports: List[Tuple[int, dict]] = []
        self.handoffs_sent = 0
        self.handoffs_recv = 0
        self._done_verdict: Optional[bool] = None
        self._done_open_t = 0.0
        # -- elastic membership (ISSUE 17) ------------------------------
        #: the agreed member set {rank: "prefill"|"decode"} — routing
        #: topology, done ledgers, and death observation all follow
        #: THIS, not the static MeshSpec. A joiner starts knowing only
        #: itself (the member round teaches it the rest); everyone
        #: else seeds from the spec.
        my_role = "prefill" if mesh.is_prefill else "decode"
        if join:
            self._members: Dict[int, str] = {mesh.rank: my_role}
        else:
            self._members = {
                r: ("prefill" if r in mesh.prefill_ranks
                    else "decode")
                for r in range(mesh.world)}
        #: a member is DECLARED dead when its consensus lease is stale
        #: past this — 2 leases by default, the same double-evidence
        #: margin the PR 16 live plane demands before flagging
        self.dead_after_s = (2.0 * lease_s if dead_after_s is None
                             else float(dead_after_s))
        #: False until the member round admits this rank: a joiner
        #: adopts the agreed routing high-water mark BEFORE it may
        #: influence routing, so it can never re-route assigned work
        self._joined = not join
        self._voted_member = False
        self._member_open_t = 0.0
        self._member_epoch = -1
        self._dead: set = set()
        #: gids orphaned by a death, waiting for re-routing — they ride
        #: the admission vote's ``requeue`` list until an assignment
        #: for them publishes
        self._requeued: set = set()
        #: per-gid handoff ledgers + void counters: the done round
        #: balances ``sent - void_sent == recv - void_recv``, so a
        #: handoff whose peer died REBALANCES instead of wedging the
        #: mesh (the monotonic sent/recv counters survive for bench)
        self._sent_log: Dict[int, int] = {}
        self._recv_log: Dict[int, int] = {}
        self.handoffs_void_sent = 0
        self.handoffs_void_recv = 0
        #: gids whose KV payload this rank claimed off a corpse: their
        #: import counts void (the sender's ledger entry was voided
        #: with the sender)
        self._scavenged: set = set()
        # -- cross-host tracing (ISSUE 14) ------------------------------
        #: injected test skew applied to EVERY wall stamp this server
        #: makes (submit/export/import) AND to its clock-sync samples —
        #: one consistent wrong clock, exactly what a skewed host is.
        #: NOTE: the explicit ``clock_skew_s`` parameter skews only
        #: THIS server (in-process multi-server protocol tests, where
        #: a per-process sink could not represent two logical clocks
        #: anyway); a run whose per-rank sinks will be MERGED must
        #: inject skew via PADDLE_CLOCK_SKEW instead, which also
        #: reaches the sink's wall-clock anchor (disttrace.walltime)
        self._skew_s = _disttrace.local_skew_s(mesh.rank) \
            if clock_skew_s is None else float(clock_skew_s)
        self.clock = _disttrace.ClockSync(
            os.path.join(shared_dir, "clock"), mesh.rank, mesh.world,
            skew_s=self._skew_s)
        self._clock_voted = False
        #: the agreed offset table {str(rank): {offset_s, unc_s}}, or
        #: None until the ``clock`` consensus round publishes
        self._clock_table: Optional[Dict[str, dict]] = None
        #: periodic clock re-sync (ISSUE 15): every ``clock_resync_s``
        #: seconds after adoption, re-run the Cristian exchange on the
        #: heartbeat; when the fresh offset moved by MORE than its
        #: uncertainty, adopt it locally and re-vote the consensus
        #: ``clock`` round (a new epoch peers join via ``pending``, the
        #: straggler-heal machinery). 0 = one-shot sync (the PR 14
        #: behavior); the reference rank never resamples (its offset
        #: is 0 by definition) but keeps serving pongs either way.
        self.clock_resync_s = float(clock_resync_s)
        self._resyncing = False
        self._resync_at = float("inf")
        #: per-gid handoff trace context of IMPORTED requests:
        #: {gid: (ctx dict from the payload, import wall stamp)}
        self._handoff_ctx: Dict[int, Tuple[dict, float]] = {}
        # -- global KV economy (ISSUE 18) -------------------------------
        #: publish local prefix digests + route on the mesh index +
        #: replicate hot chains; forced off without a prefix cache
        #: (nothing to publish). Pure host-side policy either way.
        self.prefix_routing = bool(prefix_routing) and \
            self.engine.pool.prefix is not None
        self.prefix_publish_s = float(prefix_publish_s)
        #: the adopted mesh prefix index {str(rank): digest}, merged
        #: across rounds, pruned on membership change
        self._prefix_index: Dict[str, dict] = {}
        self._voted_prefix = False
        self._prefix_open_t = 0.0
        self._published_rev = -1          # trie rev at last vote
        self._published_chains: set = set()
        self._withdrawals_due = 0         # dirty: publish immediately
        #: migration directives adopted from routing decisions where
        #: THIS rank is the chain owner: {gid: dst rank}
        self._migrate_out: Dict[int, int] = {}
        #: (dst, chain tail hash) already shipped — the same hot chain
        #: is not re-sent every round the index lags
        self._migrated_sent: set = set()
        self.prefix_migrations_out = 0
        self.prefix_migrations_in = 0
        self.prefix_migration_bytes_out = 0
        self.prefix_migration_bytes_in = 0
        self.stale_digest_withdrawals = 0
        if self.prefix_routing:
            # withdraw-before-reclaim (ISSUE 18 satellite): the hook
            # runs while the index still holds the page's refcount
            self.engine.pool.prefix.on_drop = self._on_prefix_drop
        # lease upkeep on a daemon thread: a rank COMPILING its first
        # tick (tens of seconds on a small box) is alive, and its lease
        # must say so or a fast peer transiently "survives" it and
        # decides rounds alone (Consensus.start_heartbeat docstring).
        self.consensus.start_heartbeat()
        if join:
            self._catch_up()

    def close(self) -> None:
        self.consensus.stop_heartbeat()

    def __enter__(self) -> "DisaggServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- submission (identical stream on every rank) -----------------------
    def submit(self, prompt_ids, max_new_tokens: int) -> int:
        p = np.asarray(prompt_ids, np.int32).reshape(-1)
        gid = self._next_gid
        self._next_gid += 1
        self._reqs[gid] = _GlobalReq(gid, p, int(max_new_tokens),
                                     self._walltime(),
                                     trace=_disttrace.trace_id(gid))
        # an open-ended driver (Poisson arrivals) may submit AFTER an
        # idle period already voted the mesh done — new work reopens
        # the question (the next done round sees served < seen)
        self._done_verdict = None
        if gid in self._assignments:
            # the mesh routed this gid before our driver submitted it
            # (our admission vote missed a round's window): apply the
            # published assignment now instead of orphaning it
            self._apply_assignment(gid)
        return gid

    # -- clock alignment (ISSUE 14) ----------------------------------------
    def _walltime(self) -> float:
        return _disttrace.walltime(self._skew_s)

    def _clock_round(self) -> None:
        """Non-blocking Cristian sync + consensus rounds: pump the
        ping exchange until this rank's estimate is ready, vote it
        (family ``clock``), adopt the published mesh-wide offset
        table. The reference rank keeps serving pongs forever (a
        cheap listdir on the heartbeat) so late peers can still
        sample. A rank the vote window expired OUT of the published
        table keeps sampling, self-heals its own entry the moment its
        estimate lands (its local stamps must not stay uncorrected),
        and re-votes — opening the NEXT clock epoch, which every peer
        joins via ``pending`` so the straggler's offset reaches the
        whole mesh; tables merge across epochs."""
        cons = self.consensus
        me = str(self.mesh.rank)
        healed = self._clock_table is not None and \
            me in self._clock_table
        if self.mesh.rank == self.clock.ref or not healed or \
                self._resyncing:
            self.clock.step()
        self._resync_round(me)
        if self._clock_table is not None and not healed and \
                self.clock.ready and not self._clock_voted:
            # window-expired straggler: heal locally NOW (peers may
            # already be draining), then gossip via the next epoch
            self._heal_local(self.clock.estimate())
            self._vote_clock()
        if self._clock_table is None:
            self._vote_clock()
        if self._clock_voted or cons.pending("clock"):
            # a pending round a peer opened (first sync OR a healed
            # straggler's re-round) is joined with our best estimate
            self._vote_clock()
            dec = cons.outcome("clock", reducer=_clock_reducer)
            if dec is not None:
                self._clock_voted = False
                self._adopt_clock(dec.value)

    def _heal_local(self, est: Tuple[float, float]) -> None:
        """Adopt a fresh LOCAL estimate into the table + the
        process clock state + the sink/event surfaces and re-derive
        collected TTFTs — the shared step of the straggler-heal and
        periodic-resync paths (a change to one must not silently miss
        the other; the caller follows with its own vote logic)."""
        self._clock_table[str(self.mesh.rank)] = {
            "offset_s": est[0], "unc_s": est[1]}
        _disttrace.set_clock_state(est[0], est[1], ref=self.clock.ref)
        _registry().gauge("consensus/clock_unc_ms").set(est[1] * 1e3)
        _pevents.emit("clock_sync", offset_s=est[0], unc_s=est[1],
                      ref=self.clock.ref)
        self._refresh_ttfts()

    def _resync_round(self, me: str) -> None:
        """Periodic drift tracking (ISSUE 15; retires the PR 14
        "one-shot sync, no drift tracking" residue): once the resync
        interval elapses, restart the ping exchange
        (``ClockSync.resync``) and pump it on the heartbeat; when the
        fresh estimate lands, compare it to the adopted entry — an
        offset that moved by MORE than the SUM of the two
        uncertainties is a real drift/step (two estimates each within
        ±unc of the truth can legitimately differ by up to
        unc_old + unc_new, so anything inside the summed bound is
        indistinguishable from measurement noise and must not churn
        epochs), so adopt it locally right away (our own stamps must
        not stay wrong while the round converges) and re-vote the
        ``clock`` family, opening a new epoch every peer joins via
        ``pending`` and adopts MERGED (the straggler-heal path's
        machinery, reused)."""
        if self.clock_resync_s <= 0 or self.mesh.rank == self.clock.ref:
            return
        if not self._resyncing:
            if self._clock_table is not None and me in \
                    self._clock_table and \
                    time.monotonic() >= self._resync_at:
                self.clock.resync()
                self._resyncing = True
            return
        if not self.clock.ready:
            return                    # still resampling
        self._resyncing = False
        self._resync_at = time.monotonic() + self.clock_resync_s
        est = self.clock.estimate()
        old = (self._clock_table or {}).get(me) or {}
        old_off = old.get("offset_s")
        bound = est[1] + float(old.get("unc_s") or 0.0)
        if old_off is not None and abs(est[0] - old_off) <= bound:
            return                    # within the stated uncertainty
        _registry().counter("consensus/clock_resyncs").add(1)
        self._heal_local(est)
        self._clock_voted = False
        self._vote_clock()

    def _vote_clock(self) -> None:
        """Cast this rank's clock vote in the current epoch, once,
        when its estimate exists (no-op otherwise)."""
        if self._clock_voted or not self.clock.ready:
            return
        est = self.clock.estimate()
        self.consensus.vote("clock", {"offset_s": est[0],
                                      "unc_s": est[1],
                                      "ref": self.clock.ref})
        self._clock_voted = True

    def _adopt_clock(self, value: dict) -> None:
        # MERGE across epochs: a straggler's re-round carries only
        # that epoch's voters — it must extend the table, not erase
        # the first round's entries
        table = dict(self._clock_table or {})
        table.update(value.get("offsets") or {})
        me = str(self.mesh.rank)
        if me not in table and self.clock.ready:
            # published without our vote (window expiry): our local
            # estimate still anchors our OWN sink metadata honestly
            est = self.clock.estimate()
            if est is not None:
                table[me] = {"offset_s": est[0], "unc_s": est[1]}
        self._clock_table = table
        mine = table.get(me)
        ref = int(value.get("ref", 0))
        off = None if mine is None else mine.get("offset_s")
        unc = None if mine is None else mine.get("unc_s")
        _disttrace.set_clock_state(off, unc, ref=ref,
                                   synced=mine is not None)
        if unc is not None:
            _registry().gauge("consensus/clock_unc_ms").set(unc * 1e3)
        _pevents.emit("clock_sync", offset_s=off, unc_s=unc, ref=ref)
        self._refresh_ttfts()
        if self.clock_resync_s > 0 and self._resync_at == float("inf"):
            # first adoption arms the periodic re-sync timer
            self._resync_at = time.monotonic() + self.clock_resync_s

    def _offset_of(self, rank: int) -> Tuple[float, Optional[float]]:
        """(offset_s, unc_s) of ``rank`` from the agreed table; an
        unsynced rank reads as offset 0 with unc None — uncorrected
        and explicitly unbounded, never silently exact."""
        e = (self._clock_table or {}).get(str(int(rank)))
        if e is None or e.get("offset_s") is None:
            return 0.0, None
        unc = e.get("unc_s")
        return float(e["offset_s"]), (None if unc is None
                                      else float(unc))

    # -- elastic membership (ISSUE 17) -------------------------------------
    def _topology(self) -> dict:
        """Routing topology derived from the AGREED member set — a
        dead rank has left it, a joiner has entered it. Degenerate
        guard: a mesh whose every decode member died routes everything
        to the surviving ranks (they all decode) rather than crash the
        reducer on an empty pick set."""
        prefill = sorted(r for r, ro in self._members.items()
                         if ro == "prefill")
        decode = sorted(r for r, ro in self._members.items()
                        if ro == "decode")
        if not decode:
            prefill, decode = [], (sorted(self._members)
                                   or [self.mesh.rank])
        return {"prefill": prefill, "decode": decode,
                "threshold": self.long_prompt_threshold}

    def _observe_dead(self) -> List[int]:
        """Members whose consensus lease went stale past
        ``dead_after_s`` — the evidence a ``member`` round is opened
        on. The ABSENCE of a lease file is not death evidence (mesh
        bring-up); only a lease that existed and stopped refreshing
        is."""
        ages = lease_ages(self.consensus.dir)
        me = self.mesh.rank
        return sorted(r for r in self._members
                      if r != me and ages.get(r) is not None
                      and ages[r] >= self.dead_after_s)

    def _member_round(self) -> None:
        """Non-blocking membership agreement: a rank OPENS a
        ``member`` round when it observes a death or wants to join
        (rate-limited — death evidence persists until adopted);
        everyone else joins the pending round. Every vote carries the
        voter's member table, so the reduced union teaches a joiner
        the mesh and the mesh the joiner."""
        cons = self.consensus
        if self._voted_member:
            dec = cons.outcome("member", reducer=_member_reducer)
            if dec is not None:
                self._voted_member = False
                self._adopt_members(dec)
            return
        dead = self._observe_dead()
        want = bool(dead) or not self._joined
        now = time.monotonic()
        if cons.pending("member") or \
                (want and now - self._member_open_t > 0.5):
            cons.vote("member", {
                "members": {str(r): ro for r, ro in
                            sorted(self._members.items())},
                "me": self.mesh.rank,
                "role": ("prefill" if self.mesh.is_prefill
                         else "decode"),
                "dead": dead,
                "routed": self._routed_hwm,
            })
            self._voted_member = True
            self._member_open_t = now

    def _adopt_members(self, dec) -> None:
        value = dec.value
        new = {int(r): str(ro)
               for r, ro in (value.get("members") or {}).items()}
        dead = [int(d) for d in value.get("dead", [])]
        old = dict(self._members)
        self._members = new
        self._member_epoch = int(dec.epoch)
        me = self.mesh.rank
        _registry().gauge("serving/mesh_members").set(float(len(new)))
        if new and me == min(new):
            # one membership event per transition MESH-wide (the
            # route-event idiom): the lowest surviving member announces
            for r in sorted(set(new) - set(old)):
                _registry().counter("serving/member_joins").add(1)
                _pevents.emit("member_join", member=int(r),
                              role=new[r], epoch=int(dec.epoch))
            for r in sorted(r for r in dead if r in old):
                _registry().counter("serving/member_leaves").add(1)
                _pevents.emit("member_leave", member=int(r),
                              role=old.get(r, "decode"),
                              epoch=int(dec.epoch),
                              reason="lease_expired")
        if me in new and not self._joined:
            # admitted: adopt the agreed routing high-water mark so a
            # joiner can never re-route work assigned before it came
            self._joined = True
            self._routed_hwm = max(self._routed_hwm,
                                   int(value.get("routed", 0)))
        # the mesh prefix index follows membership (ISSUE 18): an
        # agreed-out rank's published chains must stop attracting
        # routing the moment the eviction adopts
        self._prune_prefix_index()
        if me not in new and self._joined:
            self._on_evicted()
            return
        newly_dead = sorted(r for r in dead if r in old and r != me)
        if newly_dead:
            self._dead.update(newly_dead)
            self._rebalance_ledgers(newly_dead)
            self._redispatch_orphans(newly_dead)
            self._done_verdict = None

    def _rebalance_ledgers(self, newly_dead: List[int]) -> None:
        """VOID every handoff ledger entry whose peer died: the
        corpse's side of the count will never be voted again, so the
        surviving side must not wedge ``_done_reducer``'s
        sent/recv balance forever (the monotonic ``handoffs_sent`` /
        ``handoffs_recv`` counters are untouched — bench reads them)."""
        dead = set(newly_dead)
        for gid, dst in list(self._sent_log.items()):
            if dst in dead:
                del self._sent_log[gid]
                self.handoffs_void_sent += 1
        for gid, src in list(self._recv_log.items()):
            if src in dead:
                del self._recv_log[gid]
                self.handoffs_void_recv += 1

    def _redispatch_orphans(self, newly_dead: List[int]) -> None:
        """Reconstruct and re-dispatch every request orphaned by the
        dead ranks, from records every survivor already holds: the
        prompt (SPMD driver contract), the published assignment, and
        the handoff ledgers/trace contexts.

        - assigned DECODE rank died: its (possibly in-flight) result
          is gone. If an exported-KV file addressed to it survives on
          the channel, a deterministic claimer — pure function of
          (member set, gid), so every survivor repoints the assignment
          identically without another round — renames it to itself and
          audits the payload (``HandoffChannel.scavenge``); otherwise
          the gid re-routes from scratch through the next admission
          round's ``requeue`` list. Re-prefill from the prompt is the
          honest fallback, never a guessed KV state.
        - assigned PREFILL rank died, decode owner alive: only the
          decode owner acts, locally. Work that already landed (or a
          complete file in flight — sends are atomic, a corpse leaves
          only ``.tmp``) is left alone; otherwise the owner re-runs
          the prefill itself.
        """
        me = self.mesh.rank
        dead = set(newly_dead)
        mine = set(self._local.values())
        pending = {g for g, _ in self._pending_imports}
        topo = self._topology()
        live_decode = [r for r in topo["decode"] if r not in dead]
        for gid in sorted(self._reqs):
            req = self._reqs[gid]
            if not req.routed or gid in self._collected:
                continue
            p, d = req.prefill_rank, req.decode_rank
            if d in dead:
                claimer = (live_decode[gid % len(live_decode)]
                           if live_decode else -1)
                has_file = claimer >= 0 and (
                    os.path.exists(self.channel._path_to(gid, d)) or
                    os.path.exists(self.channel._path_to(gid,
                                                         claimer)))
                if has_file:
                    claimed = True
                    if claimer == me:
                        claimed = self.channel.scavenge(gid, d)
                        if claimed:
                            self._scavenged.add(gid)
                            req.meta["redispatched"] = "scavenge"
                            req.meta["redispatch_w"] = \
                                self._walltime()
                            _registry().counter(
                                "serving/redispatches").add(1)
                            _pevents.emit(
                                "redispatch", gid=gid,
                                trace=req.trace, mode="scavenge",
                                dead_rank=int(d))
                    if claimed:
                        req.decode_rank = claimer
                        self._assignments[gid] = (p, claimer)
                        self._done_verdict = None
                        continue
                self._requeue_gid(gid, dead_rank=d)
            elif p in dead:
                if d != me:
                    continue
                if gid in mine or gid in pending or \
                        gid in self._handoff_ctx or \
                        gid in self._scavenged:
                    continue        # the handoff beat the death
                if os.path.exists(self.channel._path_to(gid, me)):
                    continue        # complete and in flight: poll()
                self._reprefill_local(gid, dead_rank=p)

    def _requeue_gid(self, gid: int, dead_rank: int) -> None:
        """Send an orphaned gid back through routing: tear down any
        local work under the dead assignment, mark it unrouted, and
        let the next admission round's ``requeue`` list re-place it
        (load-shaped like any fresh arrival)."""
        req = self._reqs[gid]
        for rid, g in list(self._local.items()):
            if g != gid:
                continue
            er = self.engine._requests.get(rid)
            if er is not None and not er.done:
                self.engine.cancel(rid)
            del self._local[rid]
        req.routed = False
        req.prefill_rank = -1
        req.decode_rank = -1
        self._assignments.pop(gid, None)
        self._requeued.add(gid)
        req.meta["redispatched"] = "requeue"
        req.meta.setdefault("redispatch_w", self._walltime())
        self._done_verdict = None
        me = self.mesh.rank
        if self._members and me == min(self._members):
            # one re-dispatch event per gid mesh-wide (every survivor
            # runs this symmetrically)
            _registry().counter("serving/redispatches").add(1)
            _pevents.emit("redispatch", gid=gid, trace=req.trace,
                          mode="requeue", dead_rank=int(dead_rank))

    def _reprefill_local(self, gid: int, *, mode: str = "reprefill",
                         dead_rank: int = -1) -> None:
        """Honest fallback: THIS rank re-runs the prefill from the
        prompt it holds and decodes locally — no routing round needed,
        the route already names this rank as the visible owner."""
        req = self._reqs.get(gid)
        if req is None or gid in self._collected:
            return
        req.meta["redispatched"] = mode
        req.meta["redispatch_w"] = self._walltime()
        lr = self.engine.submit(req.prompt, req.max_new,
                                trace_id=req.trace)
        self._local[lr] = gid
        req.prefill_rank = -1
        req.decode_rank = self.mesh.rank
        req.routed = True
        self._assignments[gid] = (-1, self.mesh.rank)
        self._done_verdict = None
        _registry().counter("serving/redispatches").add(1)
        _pevents.emit("redispatch", gid=gid, trace=req.trace,
                      mode=mode, dead_rank=int(dead_rank))

    def _on_evicted(self) -> None:
        """The mesh voted US out — a false-positive death (our lease
        went stale while we kept running: long GC, suspended VM).
        Survivors requeued everything assigned here, INCLUDING work we
        already served (they cannot see our collections), so the
        honest reaction is to become a joiner again: abandon in-flight
        work, retract collected results (they re-serve elsewhere — the
        at-least-once edge the README table documents), zero our side
        of the handoff ledgers the way the survivors voided theirs,
        and re-announce through the member round."""
        self._joined = False
        for rid, gid in list(self._local.items()):
            er = self.engine._requests.get(rid)
            if er is not None and not er.done:
                self.engine.cancel(rid, reason="evicted")
            del self._local[rid]
        self._served_total -= len(self._collected)
        for gid in self._collected:
            req = self._reqs.get(gid)
            if req is not None:
                req.out = None
                req.ttft_ms = None
                req.ttft_unc_ms = None
        self._collected.clear()
        self.handoffs_void_sent = self.handoffs_sent
        self.handoffs_void_recv = self.handoffs_recv
        self._sent_log.clear()
        self._recv_log.clear()
        self._requeued.clear()
        self._migrate_out.clear()
        self._done_verdict = None
        _registry().counter("serving/self_evictions").add(1)

    def _catch_up(self) -> None:
        """Joiner bring-up: fast-forward every agreement family past
        pruned history (``Consensus.fast_forward``), then DRAIN the
        surviving decisions in order — assignments park (``submit``
        applies them when the driver replays the stream), the clock
        table and member set adopt, and stale ``done`` verdicts are
        discarded (a mesh that was idle-done before we joined must not
        make OUR ``run()`` return before we served anything)."""
        cons = self.consensus
        for fam in ("member", "clock", "admit", "done", "prefix"):
            cons.fast_forward(fam)
        while True:
            dec = cons.outcome("member", reducer=_member_reducer)
            if dec is None:
                break
            self._adopt_members(dec)
        while True:
            dec = cons.outcome("clock", reducer=_clock_reducer)
            if dec is None:
                break
            self._adopt_clock(dec.value)
        while True:
            dec = cons.outcome("prefix", reducer=_prefix_reducer)
            if dec is None:
                break
            if self.prefix_routing:
                self._adopt_prefix(dec.value)
        while True:
            dec = cons.outcome("admit", reducer=self._route_reducer)
            if dec is None:
                break
            self._adopt_assignment_decision(dec)
        while True:
            if cons.outcome("done", reducer=_done_reducer) is None:
                break
        self._done_verdict = None

    @property
    def members(self) -> Dict[int, str]:
        """The agreed member set {rank: role} as of
        ``_member_epoch``."""
        return dict(self._members)

    @property
    def redispatched(self) -> Dict[int, str]:
        """{gid: mode} of requests re-dispatched after a death as
        seen by THIS rank (mode in requeue|reprefill|scavenge) —
        bench and tests intersect this with ``results()`` for the
        re-served tail."""
        return {g: r.meta["redispatched"]
                for g, r in self._reqs.items()
                if "redispatched" in r.meta}

    # -- global KV economy (ISSUE 18) --------------------------------------
    def _on_prefix_drop(self, chain_hash: str, n_tokens: int) -> None:
        """PrefixCache eviction hook, called BEFORE the page is handed
        back to the allocator: a chain this rank may have published is
        going away, so record the withdrawal NOW — the next prefix
        round publishes immediately (no rate-limit wait), and until it
        lands a peer routing on the stale digest merely mis-prices a
        pick (the lookup on arrival is an honest miss)."""
        if chain_hash in self._published_chains:
            self._withdrawals_due += 1
            self.stale_digest_withdrawals += 1
            _registry().counter(
                "serving/stale_digest_withdrawals").add(1)
            _pevents.emit("prefix_withdraw", chain=chain_hash,
                          tokens=int(n_tokens))

    def _prefix_round(self) -> None:
        """Non-blocking digest publication through the consensus board
        (family ``prefix``): vote this rank's current trie digest when
        it CHANGED since the last vote — rate-limited, except a
        withdrawal publishes immediately — or when a peer opened the
        round; adopt the merged mesh index when it publishes. Digests
        only: chunk-hash chains + token lengths ride the board, page
        bytes ride the handoff channel and only on an agreed migrate
        directive."""
        if not self.prefix_routing:
            return
        cons = self.consensus
        if self._voted_prefix:
            dec = cons.outcome("prefix", reducer=_prefix_reducer)
            if dec is not None:
                self._voted_prefix = False
                self._adopt_prefix(dec.value)
            return
        trie = self.engine.pool.prefix
        now = time.monotonic()
        changed = trie.rev != self._published_rev
        want = changed and (
            self._withdrawals_due > 0
            or now - self._prefix_open_t > self.prefix_publish_s)
        if cons.pending("prefix") or want:
            digest = trie.digest()
            cons.vote("prefix", {"digest": digest})
            self._voted_prefix = True
            self._prefix_open_t = now
            self._published_rev = trie.rev
            self._published_chains = set(digest["chains"])
            self._withdrawals_due = 0
            _pevents.emit("prefix_publish",
                          chains=len(digest["chains"]))

    def _adopt_prefix(self, value: dict) -> None:
        for r, dig in (value.get("index") or {}).items():
            self._prefix_index[str(r)] = dig
        self._prune_prefix_index()

    def _prune_prefix_index(self) -> None:
        """Membership prunes the mesh index: an agreed-out rank's
        digests must not attract routing (its pages are gone with
        it)."""
        keep = {str(r) for r in self._members}
        for r in [r for r in self._prefix_index if r not in keep]:
            del self._prefix_index[r]

    def _route_reducer(self, votes: Dict[int, dict]) -> dict:
        """The admission reducer actually registered on the board:
        :func:`route_requests` closed over this rank's adopted mesh
        prefix index. SPMD-safe even though the index is per-rank
        state: only the round's LEADER computes the reducer — every
        other rank adopts the published decision verbatim — so index
        staleness or skew costs placement quality, never stream
        divergence."""
        return route_requests(
            votes, prefix_index=(self._prefix_index
                                 if self.prefix_routing else None))

    def _export_migrations(self) -> None:
        """Execute adopted migrate directives owned by this rank:
        replicate the hot chain's raw pages (+ scales) to the rank the
        router placed the request on, over the handoff channel's
        ``m`` family. The chain may have been evicted since the
        decision — the honest outcome is a skipped send, never a
        guessed payload."""
        if not self._migrate_out:
            return
        ps = self.engine.pool.page_size
        for gid, dst in sorted(self._migrate_out.items()):
            req = self._reqs.get(gid)
            if req is None:
                continue          # driver not caught up: retry later
            del self._migrate_out[gid]
            if dst not in self._members or dst in self._dead:
                continue
            payload = self.engine.export_prefix_chain(req.prompt)
            if payload is None:
                continue          # evicted since published: honest miss
            n_tok = int(payload["n_tokens"])
            tail = chain_hashes(req.prompt[:n_tok], ps)[-1]
            if (dst, tail) in self._migrated_sent:
                continue
            self._migrated_sent.add((dst, tail))
            nbytes = self.channel.send(dst, gid, payload, kind="m")
            self.prefix_migrations_out += 1
            self.prefix_migration_bytes_out += nbytes
            reg = _registry()
            reg.counter("serving/prefix_migrations_out").add(1)
            reg.counter("serving/prefix_migration_bytes_out") \
                .add(nbytes)
            _pevents.emit("prefix_migrate_out", gid=int(gid),
                          dst=int(dst), tokens=n_tok, bytes=nbytes,
                          kv_dtype=str(payload["kv_dtype"]))

    def _import_migrations(self) -> None:
        """Consume migrated chains addressed to this rank and insert
        them into the local trie under the normal refcount rules
        (``ServingEngine.import_prefix_chain``); the next prefix round
        republishes the grown digest, so followers of the same tenant
        route here and hit REMOTELY-prefilled pages."""
        if not self.prefix_routing:
            return
        for gid, payload in self.channel.poll(kind="m"):
            try:
                tokens = self.engine.import_prefix_chain(payload)
            except ValueError:
                _registry().counter(
                    "serving/prefix_migration_rejected").add(1)
                continue
            if not tokens:
                # pool full or nothing new: dropped. Counted — a mesh
                # whose every migration lands in a full pool is a
                # sizing problem the operator must be able to SEE.
                _registry().counter(
                    "serving/prefix_migration_dropped").add(1)
                continue
            nbytes = sum(np.asarray(payload[k]).nbytes
                         for k in ("k", "v", "k_scale", "v_scale")
                         if k in payload)
            self.prefix_migrations_in += 1
            self.prefix_migration_bytes_in += nbytes
            reg = _registry()
            reg.counter("serving/prefix_migrations_in").add(1)
            reg.counter("serving/prefix_migration_bytes_in").add(nbytes)
            _pevents.emit("prefix_migrate_in", gid=int(gid),
                          tokens=int(tokens), bytes=nbytes,
                          kv_dtype=str(payload.get("kv_dtype")))

    # -- scheduling --------------------------------------------------------
    def _unrouted(self) -> List[int]:
        # requeued gids (orphans of a death, below the high-water
        # mark) need routing exactly like never-routed ones
        return sorted(set(range(self._routed_hwm, self._next_gid))
                      | self._requeued)

    def _admission_round(self) -> None:
        """Non-blocking consensus admission: vote when there is
        anything to route (or a peer opened the round), adopt the
        assignment when it publishes."""
        cons = self.consensus
        unrouted = self._unrouted()
        if not unrouted and not cons.pending("admit"):
            return
        if not self._voted_admit:
            eng = self.engine
            free_slots = sum(r is None for r in eng._slot_rid)
            # load-shaped vote (ISSUE 15): queued-prefill-token
            # backlog (every token a new arrival's first chunk waits
            # behind — queued prompts in full, residents' remaining
            # prefill) and the rank's rolling p95 TTFT, next to the
            # free-capacity counts the old reducer used alone
            backlog = sum(int(r.prompt.shape[0]) for r in eng._queue)
            for s, rid in enumerate(eng._slot_rid):
                if rid is not None:
                    backlog += max(0, int(eng._slot_prompt[s])
                                   - int(eng._slot_len[s]))
            # rolling p95 from the scheduler's bounded finish window
            # (O(64) — walking the profiler event ring here would put
            # an O(ring) scan on every admission round)
            p95 = eng._sched.ttft_p95()
            vote = {
                "seen": self._next_gid,
                "routed": self._routed_hwm,
                "pending": {str(g): int(self._reqs[g].prompt.shape[0])
                            for g in unrouted},
                "requeue": sorted(self._requeued),
                "free_pages": int(eng.pool.allocator.num_free),
                "free_slots": int(free_slots),
                "queued": int(len(eng._queue)) + len(eng._held_ready),
                "prefill_backlog": int(backlog),
                "ttft_p95_ms": round(float(p95), 3),
                "chunk": int(eng.prefill_chunk),
                "page_size": int(eng.pool.page_size),
                # topology follows the AGREED member set, not the
                # static MeshSpec (ISSUE 17): a dead rank left it, a
                # joiner entered it
                "topology": self._topology(),
                # the agreed member set rides every admission vote
                # (ISSUE 18 satellite): an agreed-out rank's stale
                # vote or topology row is EXCLUDED by the reducer,
                # not priced as busy
                "members": sorted(self._members),
            }
            if self.prefix_routing:
                # per-gid chunk-hash chains (capped — the affinity
                # term saturates long before 32 pages) so the leader
                # can price published-prefix coverage per candidate
                ch = {}
                for g in unrouted:
                    c = chain_hashes(self._reqs[g].prompt,
                                     eng.pool.page_size)[:32]
                    if c:
                        ch[str(g)] = c
                if ch:
                    vote["chains"] = ch
            cons.vote("admit", vote)
            self._voted_admit = True
        dec = cons.outcome("admit", reducer=self._route_reducer)
        if dec is None:
            return
        self._voted_admit = False
        self._adopt_assignment_decision(dec)

    def _adopt_assignment_decision(self, dec) -> None:
        """Apply one published admission decision (the shared adoption
        step of the live round and the joiner's history catch-up)."""
        assign = dec.value["assign"]
        if assign:
            _registry().counter("consensus/requests_routed") \
                .add(len(assign))
        me = self.mesh.rank
        for g_str, (p_rank, d_rank) in sorted(assign.items(),
                                              key=lambda kv: int(kv[0])):
            gid = int(g_str)
            p_rank, d_rank = int(p_rank), int(d_rank)
            prev = self._assignments.get(gid)
            self._assignments[gid] = (p_rank, d_rank)
            self._requeued.discard(gid)
            if prev is not None and prev != (p_rank, d_rank) and \
                    gid in self._reqs and gid not in self._collected:
                # a re-dispatch OVERWROTE a stale claim (e.g. a failed
                # scavenge audit re-routed a gid the mesh had
                # repointed at the claimer): tear down local work
                # under the old assignment, re-apply under the new
                req = self._reqs[gid]
                for rid, g in list(self._local.items()):
                    if g == gid:
                        er = self.engine._requests.get(rid)
                        if er is not None and not er.done:
                            self.engine.cancel(rid)
                        del self._local[rid]
                req.routed = False
                req.prefill_rank = -1
                req.decode_rank = -1
            if d_rank == me:
                # the routing decision, as an event on the rank that
                # will OWN the visible result (one event per request
                # mesh-wide, not one per rank)
                _pevents.emit("route", gid=gid,
                              trace=_disttrace.trace_id(gid),
                              prefill=p_rank, decode=d_rank)
            if gid in self._reqs:
                self._apply_assignment(gid)
            # else: routed before our driver submitted it — submit()
            # applies the parked assignment when the gid arrives
        for g_str, sd in (dec.value.get("migrate") or {}).items():
            src, dst = int(sd[0]), int(sd[1])
            if src == me and dst != me:
                # this rank owns the hot chain: replicate it to where
                # the request will actually prefill (_export_migrations
                # runs it on the heartbeat — the prompt is known here
                # by the SPMD driver contract, so the chain is
                # recoverable from the trie even though the directive
                # carries only ranks)
                self._migrate_out.setdefault(int(g_str), dst)
        self._routed_hwm = max(self._routed_hwm,
                               int(dec.value["routed"]))

    def _apply_assignment(self, gid: int) -> None:
        req = self._reqs[gid]
        if req.routed:
            return
        req.prefill_rank, req.decode_rank = self._assignments[gid]
        req.routed = True
        me = self.mesh.rank
        if req.prefill_rank == me:
            lr = self.engine.submit(req.prompt, req.max_new,
                                    hold_after_prefill=True,
                                    trace_id=req.trace)
            self._local[lr] = gid
        elif req.decode_rank == me and req.prefill_rank < 0:
            lr = self.engine.submit(req.prompt, req.max_new,
                                    trace_id=req.trace)
            self._local[lr] = gid
        else:
            return
        if "redispatched" in req.meta:
            # the re-dispatch clock restarts at the actual re-submit:
            # TTFT accounting charges the user wait from the ORIGINAL
            # submit up to here, then the engine pair takes over
            # (same-host wall stamps — no clock correction involved)
            req.meta["redispatch_w"] = self._walltime()

    def _export_held(self) -> None:
        eng = self.engine
        for rid in eng.held_ready():
            gid = self._local.get(rid)
            if gid is None:          # not ours to ship (can't happen)
                continue
            req = self._reqs[gid]
            payload = eng.export_held(rid)
            # the prefill-rank leg of the trace rides the payload: the
            # decode rank (and the offline merger) need the submit
            # wall stamp to report a TRUE end-to-end TTFT instead of
            # the old suppressed decode-side ~0 ms pair. The engine's
            # same-host prefill TTFT (submit -> first token on THIS
            # rank) travels too — it is a clean clock pair and bounds
            # the handoff breakdown from the left.
            er = eng._requests[rid]
            prefill_ttft = None
            if er.first_token_t is not None:
                prefill_ttft = (er.first_token_t - er.submit_t) * 1e3
                req.meta["prefill_ttft_ms"] = prefill_ttft
            payload["trace_ctx"] = json.dumps({
                "trace": req.trace, "gid": gid,
                "prefill_rank": self.mesh.rank,
                "submit_w": req.submit_w,
                "export_w": self._walltime(),
                "prefill_ttft_ms": prefill_ttft,
            })
            self.channel.send(req.decode_rank, gid, payload)
            eng.release_exported(rid)
            self.handoffs_sent += 1
            # per-gid ledger entry: voided if the receiver dies before
            # the mesh's done balance can count its recv
            self._sent_log[gid] = int(req.decode_rank)

    @staticmethod
    def _payload_src(payload: dict) -> Optional[int]:
        """Sender rank from the payload's trace context (None for a
        pre-ISSUE-14 payload without one)."""
        raw = payload.get("trace_ctx")
        if raw is None:
            return None
        try:
            return int(json.loads(str(raw)).get("prefill_rank", -1))
        except (ValueError, TypeError):
            return None

    def _note_recv(self, gid: int, payload: dict) -> None:
        """Recv-side ledger bookkeeping: a scavenged payload (or one
        whose sender the mesh already declared dead) counts VOID — the
        sender's side of the balance is gone with the sender."""
        self.handoffs_recv += 1
        if gid in self._scavenged:
            self._scavenged.discard(gid)
            self.handoffs_void_recv += 1
            return
        src = self._payload_src(payload)
        if src is None:
            return                    # legacy payload: unvoidable
        if src in self._dead:
            self.handoffs_void_recv += 1
        else:
            self._recv_log[gid] = src

    def _import_arrivals(self) -> None:
        self._pending_imports.extend(self.channel.poll())
        still: List[Tuple[int, dict]] = []
        for gid, payload in self._pending_imports:
            try:
                lr = self.engine.admit_prefilled(payload)
            except ValueError:
                # the engine's admission audit rejected the payload
                # (page count / dtype — e.g. a scavenged file from a
                # mismatched corpse): never a torn import into the
                # pool — drop it and re-prefill locally, the honest
                # fallback
                _registry().counter(
                    "serving/handoff_import_rejected").add(1)
                src = self._payload_src(payload)
                self._note_recv(gid, payload)
                self._reprefill_local(
                    gid, dead_rank=-1 if src is None else src)
                continue
            if lr is None:
                still.append((gid, payload))    # no slot/pages yet
                continue
            self._local[lr] = gid
            self._note_recv(gid, payload)
            # stamp the import wall moment + keep the payload's trace
            # context: together with the agreed clock offsets they make
            # the handed-off request's end-to-end TTFT computable HERE
            # (keyed by gid, not _reqs — the import can land before our
            # driver submitted the gid)
            raw = payload.get("trace_ctx")
            if raw is not None:
                try:
                    ctx = json.loads(str(raw))
                except ValueError:   # pragma: no cover - torn context
                    ctx = None
                if ctx is not None:
                    self._handoff_ctx[gid] = (ctx, self._walltime())
                    # the channel-wait histogram sample is recorded in
                    # _stamp_e2e_ttft once the offsets are SYNCED — a
                    # histogram cannot retract a pre-adoption
                    # skew-corrupted observation the way ttft_ms can
                    # be re-derived
        self._pending_imports = still

    def _collect_finished(self) -> None:
        eng = self.engine
        # iterate OUR rid map, not the engine's whole request history:
        # the heartbeat must stay O(resident + uncollected), not
        # O(everything ever served)
        for rid, gid in list(self._local.items()):
            er = eng._requests.get(rid)
            if er is None or not er.done:
                continue
            if getattr(er, "canceled", False):
                del self._local[rid]    # re-dispatched away: no result
                continue
            if gid in self._collected:
                continue
            req = self._reqs[gid]
            if req.prefill_rank == self.mesh.rank and \
                    req.decode_rank != self.mesh.rank:
                continue            # done-by-export, not a result
            self._collected.add(gid)
            self._served_total += 1
            req.out = np.asarray(er.out, np.int32)
            # TTFT (ISSUE 14): a locally-served request keeps the
            # same-host engine clock pair; a handed-off one reports
            # the TRUE end-to-end delta — prefill-rank submit wall ->
            # this rank's import (its first-token moment), corrected
            # by the agreed clock offsets and carrying their summed
            # uncertainty. The old path suppressed the decode-side
            # pair entirely (first_token_t == submit_t at import — a
            # bogus ~0 ms) and left ttft_ms=None for every handed-off
            # request: the mesh's headline latency was unmeasurable by
            # construction.
            if req.ttft_ms is None and er.first_token_t is not None:
                if req.prefill_rank in (-1, self.mesh.rank):
                    req.ttft_ms = \
                        (er.first_token_t - er.submit_t) * 1e3
                    rw = req.meta.get("redispatch_w")
                    if rw is not None:
                        # a re-dispatched request's first token only
                        # exists because of the re-submit: the user
                        # waited from the ORIGINAL submit. Both wall
                        # stamps are this host's — no clock
                        # correction involved. (A handed-off requeue
                        # needs no term: its e2e path already anchors
                        # at the original submit_w from the ctx.)
                        req.ttft_ms += max(
                            0.0, (rw - req.submit_w) * 1e3)
                    # the live plane's mesh TTFT sketch (ISSUE 16):
                    # the engine's own serving/ttft_ms is bogus-local
                    # for imported requests, so the coordinator owns
                    # an e2e histogram — one sample per gid, the same
                    # values write_results() reports
                    _registry().histogram(
                        "serving/e2e_ttft_ms").observe(req.ttft_ms)
                else:
                    self._stamp_e2e_ttft(req)
            req.meta["finish_w"] = self._walltime()

    def _stamp_e2e_ttft(self, req: _GlobalReq) -> None:
        """End-to-end TTFT of a request handed off TO this rank:
        (import wall - our offset) - (prefill-rank submit wall - its
        offset), in the reference rank's clock, ± the two offsets'
        summed uncertainty. A payload without a trace context (a
        pre-ISSUE-14 sender) leaves ttft_ms None — honestly absent,
        never the old bogus ~0 ms."""
        ctx, import_w = self._handoff_ctx.get(req.gid, (None, None))
        if ctx is None:
            return
        o_me, u_me = self._offset_of(self.mesh.rank)
        o_p, u_p = self._offset_of(int(ctx.get("prefill_rank", -1)))
        req.ttft_ms = ((import_w - o_me)
                       - (float(ctx["submit_w"]) - o_p)) * 1e3
        if u_me is not None and u_p is not None:
            first_stamp = req.ttft_unc_ms is None
            req.ttft_unc_ms = (u_me + u_p) * 1e3
            if first_stamp:
                # exactly one synced observation per handed-off
                # request (unc transitions None -> value once)
                _registry().histogram(
                    "serving/handoff_channel_wait_ms").observe(
                    ((import_w - o_me)
                     - (float(ctx["export_w"]) - o_p)) * 1e3)
                # same latch for the live plane's e2e TTFT sketch
                # (ISSUE 16): only the offset-corrected value lands —
                # a sketch cannot retract a skew-corrupted sample the
                # way _refresh_ttfts re-derives ttft_ms
                _registry().histogram(
                    "serving/e2e_ttft_ms").observe(req.ttft_ms)

    def _refresh_ttfts(self) -> None:
        """Re-derive handed-off TTFTs from their retained trace
        contexts under the CURRENT offset table: a request collected
        while the clock round was still converging (the mesh's first
        steps are compile-heavy — imports can beat adoption) was
        stamped uncorrected with unc None; once the table exists, the
        corrected value with its bound replaces it. Idempotent; called
        on every read surface (ttfts/ttft_bounds/write_results) and at
        table adoption."""
        if self._clock_table is None:
            return
        for gid in self._handoff_ctx:
            req = self._reqs.get(gid)
            if req is not None and req.ttft_ms is not None \
                    and req.ttft_unc_ms is None:
                self._stamp_e2e_ttft(req)

    def step(self) -> bool:
        """One coordinator heartbeat. Returns whether the local engine
        dispatched device work (the driver's idle signal)."""
        self.consensus.heartbeat()
        self._clock_round()
        self._member_round()
        self._prefix_round()
        self._admission_round()
        self._export_migrations()
        self._import_arrivals()
        self._import_migrations()
        progressed = self.engine.step()
        if not progressed and self.engine._inflight:
            self.engine.drain(0)
        self._export_held()
        self._collect_finished()
        self._done_round()
        return progressed

    def _clock_settled(self) -> bool:
        """The clock round is adopted — or can never be: a dead
        reference rank answers no pings and leads no round, so waiting
        on it would hold the whole drain hostage (TTFTs then ship
        uncorrected with unc None, which is the honest degraded
        outcome, not a hang)."""
        return self._clock_table is not None or \
            self.clock.ref not in self.consensus.alive()

    def quiescent(self) -> bool:
        """Locally drained: nothing unrouted, engine idle, no parked
        imports, no unexported holds — and the clock round settled (a
        short workload must not declare the mesh done while offsets
        are still converging: collected TTFTs would ship uncorrected.
        The round terminates on any live mesh: every stepping rank
        votes, a dead non-reference rank is window-expired by the
        leader, and a dead REFERENCE releases the gate outright —
        see :meth:`_clock_settled`)."""
        eng = self.engine
        return (self._clock_settled()
                and not self._unrouted()
                and not self._pending_imports
                and not self._migrate_out
                and not eng._held_ready
                and not eng._queue and not eng._inflight
                and all(r is None for r in eng._slot_rid))

    def _done_round(self) -> None:
        """Non-blocking mesh-wide completion agreement: a ``done``
        vote round carries (idle, sent, recv, hwm) per rank; the mesh
        is done when every rank is idle with matching handoff ledgers.
        A QUIESCENT rank opens rounds (rate-limited); a BUSY rank joins
        any pending round immediately with ``idle=False`` — so no peer
        ever stalls on the vote window waiting for a rank that is
        simply working. Requires a healthy mesh: chaos tests drive
        ``step()`` + local quiescence instead (a corpse's ledger never
        balances — its unserved assignments are the documented
        residue)."""
        cons = self.consensus
        if self._voted_done:
            dec = cons.outcome("done", reducer=_done_reducer)
            if dec is not None:
                self._voted_done = False
                self._done_verdict = bool(dec.value)
            return
        q = self.quiescent()
        if cons.pending("done") or \
                (q and time.monotonic() - self._done_open_t > 0.2):
            cons.vote("done", {"idle": q,
                               "sent": self.handoffs_sent,
                               "recv": self.handoffs_recv,
                               "void_sent": self.handoffs_void_sent,
                               "void_recv": self.handoffs_void_recv,
                               "served": self._served_total,
                               "seen": self._next_gid,
                               "routed": self._routed_hwm})
            self._voted_done = True
            self._done_open_t = time.monotonic()

    def run(self, timeout_s: float = 600.0,
            poll_s: float = 0.005) -> Dict[int, np.ndarray]:
        """Drive until the mesh agrees the stream is served; returns
        the requests decoded on THIS rank ({gid: np.int32 ids})."""
        deadline = time.monotonic() + timeout_s
        while True:
            progressed = self.step()
            if self._done_verdict:
                break
            if not progressed:
                time.sleep(poll_s)      # waiting on arrivals or votes
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"disagg mesh did not drain: rank {self.mesh.rank} "
                    f"unrouted={len(self._unrouted())} "
                    f"requeued={len(self._requeued)} "
                    f"held={len(self.engine._held_ready)} "
                    f"imports={len(self._pending_imports)} "
                    f"members={sorted(self._members)} "
                    f"sent={self.handoffs_sent} recv={self.handoffs_recv} "
                    f"void={self.handoffs_void_sent}/"
                    f"{self.handoffs_void_recv}")
        return self.results()

    # -- results -----------------------------------------------------------
    def results(self) -> Dict[int, np.ndarray]:
        return {g: r.out for g, r in self._reqs.items()
                if r.out is not None}

    def reset_results(self) -> None:
        """Hand collected/forwarded requests back to the allocator: a
        long-running host must not grow ``_reqs``/``_local``/engine
        request history with every request ever served (the engine's
        ``reset_results`` idiom, lifted to the mesh level). Call after
        consuming ``results()``; mesh-wide done accounting survives
        (``_served_total`` is a monotonic counter, not a scan)."""
        drop_rids = []
        canceled_rids = []
        for rid, gid in self._local.items():
            er = self.engine._requests.get(rid)
            if er is None or not er.done:
                continue
            if getattr(er, "canceled", False):
                # re-dispatched away: free the rid, but KEEP the gid's
                # mesh state — it lives (or lived) somewhere else
                canceled_rids.append(rid)
                continue
            req = self._reqs.get(gid)
            exported = req is not None and \
                req.prefill_rank == self.mesh.rank and \
                req.decode_rank != self.mesh.rank
            if gid in self._collected or exported:
                drop_rids.append(rid)
        for rid in canceled_rids:
            self._local.pop(rid)
        for rid in drop_rids:
            gid = self._local.pop(rid)
            self._reqs.pop(gid, None)
            self._collected.discard(gid)
            self._handoff_ctx.pop(gid, None)
        self.engine.reset_results()

    def ttfts(self) -> Dict[int, float]:
        """{gid: ttft_ms} owned by the rank that served the request's
        visible result: a same-host clock pair for locally-served
        requests, the offset-corrected END-TO-END delta (prefill-rank
        submit -> this rank's first token) for handed-off ones — see
        :meth:`ttft_bounds` for the uncertainty that delta carries."""
        self._refresh_ttfts()
        return {g: r.ttft_ms for g, r in self._reqs.items()
                if r.ttft_ms is not None}

    def ttft_uncs(self) -> Dict[int, float]:
        """{gid: ± clock-uncertainty ms} for the TTFTs that are
        cross-host deltas (the handed-off requests this rank decoded);
        same-host pairs and unsynced deltas are absent."""
        self._refresh_ttfts()
        return {g: r.ttft_unc_ms for g, r in self._reqs.items()
                if r.ttft_unc_ms is not None}

    def ttft_bounds(self) -> Dict[int, Tuple[float, float, float]]:
        """{gid: (lo_ms, ttft_ms, hi_ms)} — the TTFT with its clock-
        alignment error bar. Same-host pairs have no cross-clock term
        (lo == ttft == hi); a cross-host delta widens by the two
        ranks' summed offset uncertainty; a cross-host delta measured
        WITHOUT a synced clock table is excluded (its bounds would be
        fiction)."""
        self._refresh_ttfts()
        out = {}
        for g, r in self._reqs.items():
            if r.ttft_ms is None:
                continue
            handed = r.prefill_rank not in (-1, self.mesh.rank) and \
                r.decode_rank == self.mesh.rank
            if not handed:
                out[g] = (r.ttft_ms, r.ttft_ms, r.ttft_ms)
            elif r.ttft_unc_ms is not None:
                out[g] = (r.ttft_ms - r.ttft_unc_ms, r.ttft_ms,
                          r.ttft_ms + r.ttft_unc_ms)
        return out

    def write_results(self, path: str) -> None:
        """Atomic per-rank results artifact (the test/bench drivers
        merge these instead of adding a gather collective)."""
        self._refresh_ttfts()
        doc = {
            "rank": self.mesh.rank,
            "results": {str(g): r.out.tolist()
                        for g, r in self._reqs.items()
                        if r.out is not None},
            "ttft_ms": {str(g): round(t, 3)
                        for g, t in self.ttfts().items()},
            "ttft_unc_ms": {str(g): round(u, 3)
                            for g, u in self.ttft_uncs().items()},
            "clock": _disttrace.clock_state(),
            "handoffs_sent": self.handoffs_sent,
            "handoffs_recv": self.handoffs_recv,
            "handoffs_void_sent": self.handoffs_void_sent,
            "handoffs_void_recv": self.handoffs_void_recv,
            "members": {str(r): ro
                        for r, ro in sorted(self._members.items())},
            "member_epoch": self._member_epoch,
            "redispatched": {str(g): m
                             for g, m in self.redispatched.items()},
        }
        if self.prefix_routing:
            reg = _registry()
            doc["prefix_economy"] = {
                "prefix_hit_tokens": int(reg.counter(
                    "serving/prefix_hit_tokens").value),
                "remote_hit_tokens": int(reg.counter(
                    "serving/prefix_hit_tokens_remote").value),
                "migrations_out": self.prefix_migrations_out,
                "migrations_in": self.prefix_migrations_in,
                "migration_bytes_out": self.prefix_migration_bytes_out,
                "migration_bytes_in": self.prefix_migration_bytes_in,
                "stale_withdrawals": self.stale_digest_withdrawals,
                "kv_dtype": str(np.dtype(self.engine.pool.k.dtype)),
                "published_chains": len(self._published_chains),
            }
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def check_consistency(self) -> List[str]:
        """The local pool-shard audit (multihost chaos tests run this
        on SURVIVORS after a peer died mid-handoff)."""
        return self.engine.pool.check_consistency()


def _done_reducer(votes: Dict[int, dict]) -> bool:
    """Done iff every voter is idle, the handoff ledgers balance, every
    rank has seen+routed the same stream, AND every routed request was
    actually served (each gid finishes on exactly one rank, so served
    counts sum to the stream length). The served term is what makes a
    round decided while one rank's vote is transiently missing come out
    False instead of declaring victory over its unserved work.

    Elastic rebalance (ISSUE 17): the balance nets out VOIDED
    handoffs — entries whose peer the mesh declared dead, whose side
    of the count will never be voted — so a death rebalances the
    ledgers instead of wedging them (``sent - void_sent ==
    recv - void_recv``; pre-elastic votes default the void terms to
    0). ``served == seen`` still holds because survivors re-dispatch
    and re-serve every orphaned gid."""
    idle = all(v["idle"] for v in votes.values())
    sent = sum(int(v["sent"]) - int(v.get("void_sent", 0))
               for v in votes.values())
    recv = sum(int(v["recv"]) - int(v.get("void_recv", 0))
               for v in votes.values())
    served = sum(int(v["served"]) for v in votes.values())
    seen = {int(v["seen"]) for v in votes.values()}
    routed = {int(v["routed"]) for v in votes.values()}
    return bool(idle and sent == recv and len(seen) == 1
                and routed == seen and served == seen.pop())
