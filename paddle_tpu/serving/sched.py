"""SLO-aware serving scheduler policies (ISSUE 15 tentpole).

Every *data-plane* decision of the serving engine is compiled and
fixed-shape (ONE mixed-row tick; the spec engine adds one draft tick),
but every *policy* decision used to be the naive default: chunk
selection strictly oldest-admission-first, a constant per-tick prefill
budget, one static speculation depth per engine, and page-count-only
routing votes on the disaggregated mesh. BENCH_SERVE_r13 measured the
cost of the first one directly — on a long-prompt-mixed workload the
symmetric topology's p95 TTFT loses to disagg 0.83x because every
short prompt admitted behind a long waits for the long's ENTIRE chunk
train. This module is the host-side policy layer that exploits the
sub-request granularity the chunked-prefill + ragged-tick design
already paid for:

- :class:`ChunkScheduler` — pluggable chunk-selection order
  (``ServingConfig.scheduler``):

  * ``"fifo"``  — oldest admission first, the pre-ISSUE-15 behavior and
    the default (every bitwise parity pin rides on unchanged
    scheduling, so the default must not move);
  * ``"sjf"``   — shortest-remaining-prefill first: a short prompt
    never parks behind a long chunk train. Starves long prompts under
    a continuous short flood (classic SJF pathology);
  * ``"aged-sjf"`` — SJF with deadline aging: a pending slot's
    effective priority is ``max(remaining - age_rate * waited_ticks,
    0)`` with FIFO tie-break, so every admitted request's priority
    decays to the global minimum in bounded time and
    :meth:`ChunkScheduler.starvation_bound_ticks` is a PROVABLE
    first-chunk bound (tested against a hostile flood).

  The scheduler also owns **budget shaping**: the per-tick prefill
  budget becomes a decision in ``[1, prefill_chunks_per_tick]``
  informed by decode-stall telemetry (resident decode count, queue
  depth, rolling TTFT/TPOT p95 from the event timelines). The
  compiled tick shape is UNTOUCHED — ``prefill_chunks_per_tick``
  stays the worst case the program was traced for; the policy only
  selects fewer chunks, which the fixed-shape pad rows absorb.

- :class:`SpecKController` — adaptive per-slot speculation depth
  (``SpecConfig.adaptive``): an accept-rate EWMA per slot maps to a
  draft depth in the compiled ``[0, k]`` range the verify tick already
  supports via ``row_len``. High-accept slots run full depth;
  low-accept slots decay toward ``k = 0`` — a plain decode row, so a
  hopeless draft stops costing verify width. Closes the PR 9 residue
  ("adaptive k is a scheduler policy follow-up") without touching
  either compiled site.

- :func:`ttfc_key` — the load-shaped routing score used by
  ``serving/disagg.py::route_requests``: estimated time-to-first-chunk
  (queued-prefill-token backlog in chunk-train units + slot-overflow
  penalty, rolling p95 TTFT as the tie-break) instead of free pages
  alone. Pure, rank-deterministic, same consensus round.

Nothing here dispatches device work or changes a compiled program:
every policy only reorders/limits HOST-side selection, so
``compiled_sites`` and the single-trace contract are untouched under
every policy (asserted in tests/test_sched.py).

Profiler signals: ``serving/aged_promotions`` (aging changed a pick
pure SJF would have made differently), ``serving/budget_cuts`` (ticks
whose shaped budget < the compiled worst case; counted by the engine),
``serving/chunk_wait_ms`` (admission -> first chunk open, engine-side),
``serving/spec_k_effective`` (mean offered draft depth per spec tick,
engine-side).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..profiler.metrics import registry as _registry

__all__ = ["SCHED_POLICIES", "ChunkScheduler", "SpecKController",
           "ttfc_key"]

#: ServingConfig.scheduler values, in documentation order
SCHED_POLICIES = ("fifo", "sjf", "aged-sjf")


class ChunkScheduler:
    """Host-side chunk-selection + prefill-budget policy.

    The engine calls, per scheduler step:

    - :meth:`on_tick` once (advances the aging clock);
    - :meth:`chunk_budget` once (how many chunks to select this tick);
    - :meth:`pick` once per selected chunk (which pending slot opens
      the next chunk), with candidates ``(slot, admit_seq,
      remaining_prefill_tokens)``;
    - :meth:`note_admit` / :meth:`note_open` / :meth:`note_release` at
      the matching slot-lifecycle edges (aging bookkeeping).

    ``fifo`` reproduces the pre-ISSUE-15 behavior EXACTLY (min
    admit_seq, constant budget) — the default configuration's
    scheduling is bit-for-bit the old engine's, which is what keeps
    every existing bitwise parity pin undisturbed by construction.
    """

    def __init__(self, policy: str, num_slots: int,
                 slot_capacity: int, prefill_chunk: int,
                 chunks_per_tick: int, *,
                 age_rate_tokens: Optional[int] = None,
                 stats_every: int = 16):
        if policy not in SCHED_POLICIES:
            raise ValueError(
                f"unknown scheduler policy {policy!r}; expected one of "
                f"{SCHED_POLICIES}")
        self.policy = policy
        self.num_slots = int(num_slots)
        self.slot_capacity = int(slot_capacity)
        self.prefill_chunk = int(prefill_chunk)
        self.chunks_per_tick = int(chunks_per_tick)
        #: priority decay per waited tick, in remaining-prefill
        #: tokens. Default a quarter-chunk per tick: gentle enough
        #: that one admission burst's shorts clear before a parked
        #: long re-promotes into their chunk queue (promoting it
        #: mid-burst would re-create a slice of the fifo pathology),
        #: firm enough that the starvation bound stays O(capacity)
        #: ticks — ~4*ceil(cap/chunk) to the floor.
        self.age_rate = int(age_rate_tokens
                            or max(1, prefill_chunk // 4))
        #: budget shaping is a property of the non-FIFO policies: fifo
        #: must keep the constant pre-ISSUE-15 budget (parity pins)
        self.shape_budget = policy != "fifo"
        self._tick = 0
        #: tick at which each slot last opened a chunk (or was
        #: admitted) — the aging anchor
        self._anchor = np.zeros(self.num_slots, np.int64)
        #: observability: worst admission->first-chunk wait seen, in
        #: ticks (the starvation-bound test reads this)
        self.max_wait_ticks_seen = 0
        self._first_open_pending = [False] * self.num_slots
        # budget-shaping telemetry: the engine feeds each finished
        # request's TTFT/TPOT directly (note_finish — O(1) per
        # request; walking the profiler event ring per tick would put
        # an O(ring) scan on the hot loop), percentiles refresh every
        # ``stats_every`` ticks over the bounded recent window (the
        # same nearest-rank convention + bounded-window approximation
        # as profiler.request_latency_stats)
        self._stats_every = max(1, int(stats_every))
        self._ttft_window: deque = deque(maxlen=64)
        self._tpot_window: deque = deque(maxlen=64)
        self._ttft_p95 = 0.0
        self._tpot_p95 = 0.0
        # slow EWMA baselines the current percentiles compare against
        # ("rising vs its own recent past", not an absolute ms bar —
        # absolute bars would be machine-speed-dependent)
        self._ttft_ref = 0.0
        self._tpot_ref = 0.0

    # -- lifecycle bookkeeping ---------------------------------------------
    def on_tick(self) -> None:
        """One scheduler step elapsed (the aging clock)."""
        self._tick += 1
        if self.shape_budget and self._tick % self._stats_every == 0:
            self._refresh_stats()

    def note_admit(self, slot: int) -> None:
        self._anchor[slot] = self._tick
        self._first_open_pending[slot] = True

    def note_open(self, slot: int) -> None:
        """Slot opened a chunk: its aging restarts, and the first open
        of an admission cycle records the observed wait."""
        waited = int(self._tick - self._anchor[slot])
        if self._first_open_pending[slot]:
            self._first_open_pending[slot] = False
            self.max_wait_ticks_seen = max(self.max_wait_ticks_seen,
                                           waited)
        self._anchor[slot] = self._tick

    def note_release(self, slot: int) -> None:
        """Slot freed (finish/preempt/export) mid-wait: drop the
        pending-first-open latch so a requeue's wait restarts."""
        self._first_open_pending[slot] = False

    def note_finish(self, ttft_ms: Optional[float],
                    tpot_ms: Optional[float]) -> None:
        """One finished request's latency sample (engine ``_finish``
        feeds this) — the budget shaper's rolling TTFT/TPOT source."""
        if ttft_ms is not None:
            self._ttft_window.append(float(ttft_ms))
        if tpot_ms is not None:
            self._tpot_window.append(float(tpot_ms))

    def ttft_p95(self) -> float:
        """Rolling TTFT p95 over the bounded recent window, computed
        fresh (O(window log window), window <= 64) — the cheap read
        the disagg admission vote uses instead of re-deriving the
        percentile from the whole profiler event ring every round.
        Fed for EVERY policy (note_finish is unconditional); 0.0
        until the first finish."""
        if not self._ttft_window:
            return 0.0
        from ..profiler.metrics import percentile

        return float(percentile(sorted(self._ttft_window), 95))

    # -- chunk selection ----------------------------------------------------
    def pick(self, cands: Sequence[Tuple[int, int, int]]
             ) -> Optional[int]:
        """Choose the next slot to open a prefill chunk from
        ``cands = [(slot, admit_seq, remaining_prefill_tokens), ...]``.
        Returns the slot, or None when no candidate is pending."""
        if not cands:
            return None
        if self.policy == "fifo":
            return min(cands, key=lambda c: c[1])[0]
        if self.policy == "sjf":
            # shortest remaining prefill first; FIFO tie-break keeps
            # the order total and deterministic
            return min(cands, key=lambda c: (c[2], c[1]))[0]
        # aged-sjf: effective priority = remaining minus the aging
        # credit, floored at 0 — the floor is what makes the
        # starvation bound provable (an aged slot's priority reaches
        # the global minimum and FIFO tie-break takes over)
        def key(c):
            slot, seq, rem = c
            waited = self._tick - int(self._anchor[slot])
            return (max(rem - self.age_rate * waited, 0), seq)

        best = min(cands, key=key)
        if best[2] > min(c[2] for c in cands):
            # aging promoted a slot pure SJF would have passed over
            _registry().counter("serving/aged_promotions").add(1)
        return best[0]

    def starvation_bound_ticks(self) -> int:
        """Upper bound on admission -> first chunk open under
        ``aged-sjf`` (PROVABLE, assuming at least one chunk is opened
        per tick while any slot is pending — :meth:`chunk_budget`'s
        floor of 1 plus the engine's try-next-candidate-on-failure
        selection deliver this whenever any pending slot CAN acquire
        its pages; a pool so pressured that NO pending slot can open
        resolves through the preemption machinery, outside this
        bound):

        - a pending slot's effective priority hits the floor (0) after
          at most ``ceil(slot_capacity / age_rate)`` waited ticks
          (remaining <= slot_capacity always);
        - at the floor it can lose only to other floor-priority slots
          with OLDER admit_seq — at most ``num_slots - 1`` of them,
          each needing at most ``ceil(slot_capacity / prefill_chunk)``
          chunks to finish prefill and stop competing;

        so the wait is bounded by ``ceil(cap / age_rate) +
        (num_slots - 1) * ceil(cap / chunk) + 1`` ticks. Not tight —
        the hostile-flood test asserts observed <= this."""
        cap = self.slot_capacity
        to_floor = -(-cap // self.age_rate)
        chunks_per_slot = -(-cap // self.prefill_chunk)
        return to_floor + (self.num_slots - 1) * chunks_per_slot + 1

    # -- budget shaping -----------------------------------------------------
    def _refresh_stats(self) -> None:
        """Refresh the rolling TTFT/TPOT p95 over the bounded recent
        window and fold them into the slow baselines."""
        from ..profiler.metrics import percentile

        self._ttft_p95 = float(percentile(
            sorted(self._ttft_window), 95)) if self._ttft_window \
            else 0.0
        self._tpot_p95 = float(percentile(
            sorted(self._tpot_window), 95)) if self._tpot_window \
            else 0.0
        # slow EWMA (alpha 0.25): the reference tracks the run's own
        # recent latency so "rising" is relative, not absolute
        for cur, ref in (("_ttft_p95", "_ttft_ref"),
                         ("_tpot_p95", "_tpot_ref")):
            c = getattr(self, cur)
            if c > 0.0:
                r = getattr(self, ref)
                setattr(self, ref, c if r == 0.0 else
                        0.75 * r + 0.25 * c)

    def chunk_budget(self, pending_prefill: int, resident_decodes: int,
                     queue_depth: int) -> int:
        """Per-tick prefill budget in ``[1, chunks_per_tick]`` (the
        compiled worst case is the hard cap — the tick shape never
        retraces; a smaller selection rides the fixed shape's pad
        rows). FIFO returns the constant pre-ISSUE-15 budget.

        Shaping logic (deterministic, host-only):

        - **decode-stall pressure** — when at least half the slots are
          actively decoding and nothing is queued behind the pending
          prefills, every extra chunk row only stalls resident decode
          tokens (a chunk adds ``prefill_chunk`` tokens of compute to
          the tick every decode token waits behind): halve the budget;
          if the rolling TPOT p95 has risen >= 1.5x above its own
          recent baseline, cut to the floor of 1.
        - **TTFT pressure** — a queue backlog (arrivals waiting for
          slots) or a rolling TTFT p95 >= 1.5x its baseline buys the
          full budget back: prefill throughput is what drains it.

        The floor of 1 whenever anything is pending is load-bearing:
        the aged-sjf starvation bound assumes at least one chunk opens
        per tick while a slot is pending."""
        npf = self.chunks_per_tick
        if not self.shape_budget or pending_prefill <= 0 or npf <= 1:
            return npf
        budget = npf
        if queue_depth == 0 and 2 * resident_decodes >= self.num_slots:
            budget = max(1, npf // 2)
            if self._tpot_ref > 0.0 and \
                    self._tpot_p95 >= 1.5 * self._tpot_ref:
                budget = 1
        if queue_depth > 0 or (
                self._ttft_ref > 0.0
                and self._ttft_p95 >= 1.5 * self._ttft_ref):
            budget = npf
        return budget


class SpecKController:
    """Adaptive per-slot speculation depth (``SpecConfig.adaptive``).

    Per-slot accept-rate EWMA ``a_s`` (tokens accepted / tokens
    drafted per verify tick, alpha ``ewma_alpha``), mapped to a draft
    depth ``floor(a_s * k + 0.5)`` clamped to the compiled ``[0, k]``
    range. New tenants start optimistic (``a_s = 1`` -> full depth —
    the draft must earn its demotion, not its promotion, because an
    un-speculated slot generates no evidence).

    **Re-probing** (ISSUE 16 satellite, closing the PR 15 residue): a
    slot that decays to depth 0 becomes a plain decode row and stops
    producing observations — without a probe it would stay at 0 for
    its whole residency even if its accept rate recovered (a request
    leaving a hard-to-predict span for boilerplate). Every
    ``reprobe_every``-th :meth:`tick_depth` call at depth 0 drafts at
    depth 1; the probe's :meth:`observe` then either re-opens the
    EWMA (an accepted probe at alpha 0.5 lifts ``a_s`` to ~0.5 — back
    above the depth-1 line) or confirms the demotion (cost: one
    drafted token per ``reprobe_every`` ticks). The probe flag LATCHES
    until its observation lands — draft-feed catch-up can take ticks,
    and a probe that fizzles before drafting must not count as
    evidence. ``reprobe_every=0`` disables (the documented PR 15
    behavior). :meth:`depth` stays pure; only ``tick_depth`` advances
    probe state, so the engine calls it exactly once per slot per
    tick. Admission/preemption/finish still :meth:`reset` the slot.

    **Backoff** (ISSUE 20 satellite, closing the "probe period is
    static, not learned" residue): each consecutive REJECTED probe
    doubles the slot's re-probe period, capped at ``8 *
    reprobe_every`` — a slot that keeps confirming its demotion gets
    probed geometrically less often, so the steady-state probe tax on
    a genuinely unpredictable request decays toward one drafted token
    per ``8 * reprobe_every`` ticks instead of staying flat. An
    ACCEPTED probe (or any observation with ``accepted > 0``) resets
    the period to the base — recovery is detected at full cadence
    again. :meth:`probe_period` exposes the current per-slot period.

    Depth changes never touch the compiled verify tick: ``k_s`` rides
    the existing per-slot ``row_len``/``tok_limit`` metadata, exactly
    like the budget/headroom clamps the engine already applies."""

    def __init__(self, num_slots: int, k: int,
                 ewma_alpha: float = 0.5, reprobe_every: int = 0):
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if reprobe_every < 0:
            raise ValueError("reprobe_every must be >= 0")
        self.k = int(k)
        self.alpha = float(ewma_alpha)
        self.reprobe_every = int(reprobe_every)
        self._ewma = np.ones(int(num_slots), np.float64)
        self._zero_ticks = np.zeros(int(num_slots), np.int64)
        self._probing = np.zeros(int(num_slots), bool)
        self._period = np.full(int(num_slots), int(reprobe_every),
                               np.int64)

    def reset(self, slot: int) -> None:
        self._ewma[slot] = 1.0
        self._zero_ticks[slot] = 0
        self._probing[slot] = False
        self._period[slot] = self.reprobe_every

    def depth(self, slot: int) -> int:
        """Pure depth read (no probe side effects) — callers inside a
        tick use the engine's cached :meth:`tick_depth` result."""
        return int(min(self.k,
                       int(self._ewma[slot] * self.k + 0.5)))

    def tick_depth(self, slot: int) -> int:
        """The slot's depth for THIS draft tick, advancing re-probe
        state: counts consecutive depth-0 ticks and returns 1 (the
        probe) every ``reprobe_every``-th one. Call once per slot per
        scheduler tick."""
        d = self.depth(slot)
        if d > 0 or self.reprobe_every == 0:
            self._zero_ticks[slot] = 0
            return d
        if self._probing[slot]:
            return 1                # probe still awaiting evidence
        self._zero_ticks[slot] += 1
        if self._zero_ticks[slot] >= self._period[slot]:
            self._zero_ticks[slot] = 0
            self._probing[slot] = True
            return 1
        return 0

    def observe(self, slot: int, accepted: int, drafted: int) -> None:
        if drafted <= 0:
            return
        if self._probing[slot]:
            # multiplicative backoff on a rejected probe; base cadence
            # restored the moment any draft token lands
            if accepted > 0:
                self._period[slot] = self.reprobe_every
            else:
                self._period[slot] = min(self._period[slot] * 2,
                                         self.reprobe_every * 8)
        elif accepted > 0:
            self._period[slot] = self.reprobe_every
        self._probing[slot] = False     # the probe's evidence landed
        rate = min(max(accepted / drafted, 0.0), 1.0)
        self._ewma[slot] += self.alpha * (rate - self._ewma[slot])

    def probe_period(self, slot: int) -> int:
        """Current re-probe period for ``slot`` (base
        ``reprobe_every``, doubled per consecutive rejected probe,
        capped at 8x)."""
        return int(self._period[slot])

    def ewma(self, slot: int) -> float:
        return float(self._ewma[slot])

    def probing(self, slot: int) -> bool:
        return bool(self._probing[slot])


# ---------------------------------------------------------------------------
# load-shaped routing (serving/disagg.py::route_requests reducer)
# ---------------------------------------------------------------------------
def ttfc_key(votes: Dict[int, dict], rank: int,
             extra_tokens: Dict[int, int],
             extra_reqs: Dict[int, int]) -> Tuple[float, float, int]:
    """Deterministic estimated-time-to-first-chunk sort key of
    ``rank`` given one consensus round's votes (smaller = route here).

    Primary term: the rank's queued-prefill-token backlog (vote key
    ``prefill_backlog``; falls back to ``queued * chunk`` for a
    pre-ISSUE-15 voter) plus what this round already assigned it,
    in CHUNK-TRAIN units (``ceil(tokens / prefill_chunk)`` — a new
    arrival's first chunk waits behind exactly that many chunk
    selections), plus a slot-overflow penalty (arrivals beyond the
    rank's free slots wait a whole residency, not a chunk train: 8
    chunk-units each — the old reducer's queued:free_slots weight
    ratio, kept so mixed-version meshes still order sanely), plus a
    PAGE-pressure penalty (projected tokens beyond the rank's free
    page capacity — ``free_pages * page_size`` — cost preemption
    churn, not just a chunk wait: 4 chunk-units per deficit chunk,
    so the backlog term the old ``-free_pages`` load kept is not
    lost). Secondary term: the rank's rolling p95 TTFT
    (``ttft_p95_ms``; 0 when absent) — measured pressure breaks
    backlog ties toward the rank actually serving first tokens
    faster. Final tie-break: the rank id (total order; every leader
    computes the same assignment).

    A rank with no vote this round prices as unroutable-busy (the
    pre-existing dead-peer rule). Pure function of the votes — the
    reducer stays rank-deterministic and rides the SAME consensus
    round as before."""
    v = votes.get(rank)
    if v is None:
        return (float(1 << 20), float(1 << 20), rank)
    chunk = max(1, int(v.get("chunk", 64)))
    backlog = v.get("prefill_backlog")
    if backlog is None:                 # pre-ISSUE-15 voter
        backlog = int(v.get("queued", 0)) * chunk
    tokens = int(backlog) + int(extra_tokens.get(rank, 0))
    chunks_ahead = -(-tokens // chunk)
    over = max(0, int(extra_reqs.get(rank, 0))
               + int(v.get("queued", 0))
               - int(v.get("free_slots", 0)))
    # page pressure: tokens routed past the rank's free page capacity
    # trigger the preemption escalation there — far costlier than a
    # chunk wait, so weight each deficit chunk heavily
    free_tokens = int(v.get("free_pages", 0)) * \
        int(v.get("page_size", 16))
    deficit = max(0, tokens - free_tokens)
    p95 = float(v.get("ttft_p95_ms") or 0.0)
    return (float(chunks_ahead + 8 * over
                  + 4 * (-(-deficit // chunk))), p95, rank)


def prefix_affinity_key(votes: Dict[int, dict], rank: int,
                        extra_tokens: Dict[int, int],
                        extra_reqs: Dict[int, int],
                        hit_tokens: int) -> Tuple[float, float, int]:
    """:func:`ttfc_key` with a prefix-affinity discount (ISSUE 18): a
    rank holding ``hit_tokens`` of the request's published prefix
    skips that much prefill work, so the hit is priced in the SAME
    currency as the load term — chunk-train units — rather than as an
    absolute preference. A hot rank with a long backlog still loses to
    an idle rank once the backlog outweighs the saved chunks, which is
    what keeps affinity from swamping it. A rank with no vote stays
    unroutable-busy regardless of its published prefix (a digest on
    the board is no proof of life — the vote is)."""
    load, p95, r = ttfc_key(votes, rank, extra_tokens, extra_reqs)
    v = votes.get(rank)
    if v is None or hit_tokens <= 0:
        return (load, p95, r)
    chunk = max(1, int(v.get("chunk", 64)))
    return (load - float(hit_tokens // chunk), p95, r)
