"""Continuous-batching serving engine over the paged KV cache.

The dense ``GPT.generate`` path is one jitted prefill+scan program per
request batch: every admitted prompt pays ``S_max`` of cache HBM,
nobody can join or leave mid-decode, and mixed prompt lengths force
padding waste or a retrace. This engine restructures serving the way
the roadmap's cross-replica-sharding paper restructures the weight
update — so the hardware never idles on work another request could
fill:

- **ONE unified mixed-row tick.** A single jitted program per
  scheduler step carries EVERY token in flight as a ragged row —
  resident decodes (one-token rows) and up to
  ``prefill_chunks_per_tick`` prompt chunks (``prefill_chunk``-token
  rows) execute in the same program, through one
  ``ops/paged_attention.ragged_paged_attention`` call per layer over
  per-row ``(pos0, true_len)`` metadata ("Ragged Paged Attention",
  PAPERS.md). The pre-unification design's TWO dispatch sites (a
  decode tick plus a separate suffix-prefill program alternating on
  the hot path) collapse to one; the program shape never depends on
  the prefill/decode mix, so it traces exactly once (asserted via
  ``profiler.recompile`` telemetry). Per-request sampling params ride
  as ``[num_slots]`` arrays — no retrace per parameter combination.
  ``attention_kernel="legacy"`` keeps the old two-dispatch engine as
  an explicit benchmarking fallback (`serve_bench.py
  --attention-kernel`); its math routes through the same shared
  attention helper, so outputs stay bitwise-equal across modes.
- **Chunked prefill** (Sarathi-style piggybacking). A prompt is
  prefilled in fixed-size chunks riding the unified tick, at most
  ``prefill_chunks_per_tick`` per scheduler step, each attending over
  (aliased prefix pages + earlier chunks + itself). A long prompt
  therefore never blocks resident decode slots for more than one
  chunk's compute, and chunks add ZERO extra dispatches or compiled
  programs.
- **Prefix caching.** Fully-written prompt pages are registered in a
  hash-trie index (``paged_cache.PrefixCache``) keyed on page-aligned
  token chunks. Admission looks up the longest cached prefix, aliases
  those pages into the slot's table (refcounted — a page frees only
  when its last holder lets go), and prefills only the suffix; a
  prompt diverging from a cached chunk mid-page copy-on-writes that
  one tail page. Unreferenced cached pages are evicted LRU under pool
  pressure. Preemption inserts the victim's own fully-written pages
  before releasing the slot, so the requeued request re-aliases its
  own work instead of re-prefilling it.
- **Deferred host sync** (the PR-3 async-pipeline idiom): each tick's
  token vector stays an unmaterialized device array; the host
  dispatches tick N+1 before materializing tick N, keeping up to
  ``max_inflight`` ticks in flight. Scheduling that must be
  host-deterministic (positions, page growth, max-token stops) never
  reads device data; only EOS discovery rides the lagged window.
- **Exhaustion → eviction → preemption.** If the pool cannot grow a
  slot, the engine evicts unreferenced cached pages, drains, retries,
  then preempts the youngest request: its generated prefix is requeued
  as a longer prompt (and its pages stay cached, so re-prefill is a
  prefix hit). Sampling keys are folded per absolute position, so a
  preempted request's tokens do not depend on scheduling.
- **Quantized KV pages** (``ServingConfig.kv_dtype``; ISSUE 12):
  ``"int8"`` stores the pools as int8 with per-page per-head f32
  scales — 4x tokens per pool byte (2x resident slots at matched
  bytes with headroom to spare). Quantize-on-write rides INSIDE the
  one tick (``ops/paged_attention.paged_kv_scatter``: running
  scatter-max scales, rescale-on-growth, recycled pages reset via the
  fresh-page vector folded into the tick args), dequantization rides
  inside the one shared attention gather, and scales travel every
  refcount edge (COW copies the donor's scales; the null page keeps
  scale 0). ``compiled_sites`` is unchanged — int8 is a dtype of the
  one mixed-row tick, not a new dispatch site. Greedy parity vs the
  f32 engine becomes a measured token-match rate (``serve_bench
  --kv-dtype``); two int8 engines still agree bitwise. ``"bf16"``
  halves the pool with a plain cast; legacy mode keeps the model
  dtype.
- **Speculative decoding** (``ServingConfig.spec``; serving/spec.py):
  a draft model runs ``k`` tokens ahead per slot, ONE verify/mixed
  tick scores every slot's ``(1+k)``-token row (a verify row is a
  chunk row whose logits are kept at every position), greedy
  acceptance emits the target's own argmax stream — so spec greedy is
  BITWISE plain greedy — and rejected tails rewind through the
  refcounted ``shrink_slot`` path. Two compiled sites (draft tick +
  verify tick), per-tick host sync instead of the deferred window.

Greedy paged decode is **bitwise identical** to the dense
``generate()`` on the same weights whenever the slot capacity
``pages_per_slot * page_size`` equals the dense path's
``prompt + max_new_tokens`` (the attention reduction length must match
exactly — zero-tail padding is not bitwise-neutral). The unified tick
preserves this: per-token results are independent of which other rows
share the program (see ``gpt_ragged_apply``'s contract), and prefix
caching preserves it too (aliased pages hold KV that is identical by
construction), so the cached engine, the uncached engine, the legacy
two-dispatch engine and the dense path all agree —
tests/test_serving.py pins cached-vs-uncached-vs-legacy across
admission orders.

Profiler signals: ``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util``, ``serving/ttft_ms`` (histogram),
``serving/prefill_queue_wait_ms`` (histogram: submit → first prefill
chunk, FRESH admissions only), ``serving/requeue_wait_ms`` (histogram:
preempt → re-prefill start — requeue cycles used to fold back into the
submit-anchored wait, conflating scheduler delay with preemption
cost), ``serving/tokens_per_sec``, ``serving/tokens_generated``,
``serving/prefills``, ``serving/prefill_chunks``, ``serving/ticks``,
``serving/preemptions``, ``serving/requests_finished``,
``serving/token_syncs``, ``serving/prefix_lookups``,
``serving/prefix_hit_tokens``, ``serving/mixed_rows`` (+ the
``_decode``/``_prefill`` split: rows of each kind in the last unified
tick — a dispatch-site regression shows up here and in the
``serving.tick`` single-trace assertion); refcount traffic under
``cache_share/*`` (shares, releases, cow_copies, prefix_evictions).
Scheduler-policy signals (ISSUE 15): ``serving/chunk_wait_ms``
(histogram: admission -> first chunk open per admission cycle),
``serving/aged_promotions`` (aged-sjf picks pure SJF would have
ordered differently), ``serving/budget_cuts`` (ticks whose shaped
prefill budget came in under the compiled worst case),
``serving/spec_k_effective`` (mean offered draft depth per spec
tick under adaptive k).

Event timeline (ISSUE 8; profiler/events.py): every request lifecycle
edge emits a typed event into the profiler's bounded event log —
``submit``, ``admit``, ``prefix_hit``, ``cow_copy``, ``chunk`` (one
per dispatched prefill chunk), ``first_token``, ``preempt``,
``requeue``, ``finish`` (stamped with ``ttft_ms``/``tpot_ms``/
``tokens``/``reason``) — each tagged with the engine id (``eng``) and
request id, so ``profiler.latency_breakdown(rid)`` reconstructs queue
wait / prefill / decode / preempted time per request and
``ServingEngine.latency_stats(window_s=...)`` reports rolling-window
TTFT/TPOT p50/p90/p95/p99. Emission is lifecycle-edge-rate (O(1) per
residency period, never per token or per tick), so the decode hot
loop pays one bool read; serve_bench measures the residual honestly.
``record_program_stats()`` folds each compiled hot-path program's
compile wall-time + ``cost_analysis()`` FLOPs/bytes into the
profiler's program inventory, keyed by ``compiled_sites``.
"""
from __future__ import annotations

import time
import warnings
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..profiler import events as _events
from ..profiler import recompile as _recompile
from ..profiler import registry as _registry
from .paged_cache import PagePool
from .sched import SCHED_POLICIES, ChunkScheduler, SpecKController
from .spec import SpecConfig

__all__ = ["ServingConfig", "ServingEngine", "Request", "SpecConfig"]

#: engine ids stamped on every event (``eng`` attr) so co-resident
#: engines' timelines don't alias in the process-global log
_ENGINE_SEQ = iter(range(1 << 20))


def _proc_index() -> int:
    """The jax process index (0 when jax.distributed never came up) —
    folded into engine ids so co-resident engines ACROSS processes of a
    multi-host mesh stop colliding in merged ``latency_table()`` views
    (PR 8 noted the per-process sequence already reuses ids across
    processes; rank-merged sinks made that visible). ONE detection
    helper: the sink's, which guards against forcing backend bring-up
    when jax.distributed was never initialized."""
    from ..profiler.sink import _detect_rank

    return _detect_rank()

#: attention_kernel values: the unified mixed-row tick on the XLA
#: gather spelling (measured default), the unified tick on the Pallas
#: ragged kernel (interpret-verified; real-TPU measurement pending per
#: the int8_matmul precedent), and the pre-unification two-dispatch
#: engine (decode tick + separate prefill program) kept for
#: benchmarking the dispatch collapse.
ATTENTION_KERNELS = ("ragged-xla", "ragged-pallas", "legacy")


@contextmanager
def _quiet_donation():
    """CPU jax may decline buffer donation for the page pools; the
    fallback copy is correct, just slower — don't spam the log for it.
    Scoped to the engine's own dispatches: a global filter would also
    swallow the training stack's donation-failure warnings (a real perf
    signal in hybrid.py's jitted step)."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    """Engine knobs. Pool sizing math: the pool holds
    ``num_pages - 1`` allocatable pages (page 0 is the null page) of
    ``page_size`` tokens each, shared by ``num_slots`` resident
    requests of at most ``pages_per_slot`` pages
    (``slot_capacity = pages_per_slot * page_size`` tokens). Sizing
    ``num_pages - 1 < num_slots * pages_per_slot`` oversubscribes the
    pool — legal, served by prefix-cache eviction then preemption when
    it binds. With ``prefix_cache`` on, shared prompt pages are charged
    ONCE regardless of how many slots alias them, so effective
    capacity grows with prompt overlap."""

    num_slots: int = 8
    page_size: int = 16
    pages_per_slot: int = 0          # default: ceil(max_seq_len / page_size)
    num_pages: int = 0               # default: full residency + null page
    prefill_chunk: int = 0           # tokens per prefill chunk (0: 2 pages)
    prefill_chunks_per_tick: int = 1  # prefill rows per unified tick
    #: chunk-selection policy (ISSUE 15; serving/sched.py): 'fifo'
    #: (oldest admission first — the default, scheduling bit-for-bit
    #: the pre-policy engine's so every bitwise parity pin is
    #: undisturbed), 'sjf' (shortest-remaining-prefill first) or
    #: 'aged-sjf' (SJF + deadline aging with a provable starvation
    #: bound). Non-fifo policies also shape the per-tick prefill
    #: budget from decode-stall telemetry, capped at the compiled
    #: ``prefill_chunks_per_tick`` worst case — the tick shape never
    #: retraces. Host-side only: per-request outputs stay bitwise
    #: identical under every policy; only the interleaving moves.
    scheduler: str = "fifo"
    prefix_cache: bool = True        # share prompt-prefix pages
    max_inflight: int = 2            # unmaterialized decode ticks in flight
    decode: str = "greedy"           # 'greedy' | 'sampling'
    #: page-pool storage dtype (ISSUE 12): None keeps the model dtype
    #: (the bitwise-parity default), 'f32'/'bf16' store at that dtype,
    #: 'int8' quantizes pages on write with per-page per-head scales —
    #: 4x tokens per pool byte vs f32, greedy parity becomes a measured
    #: token-match rate (serve_bench --kv-dtype) instead of bitwise.
    #: Unified tick + both ragged kernels only (legacy is the
    #: pre-unification bench baseline and stays at the model dtype).
    kv_dtype: Optional[str] = None   # None | 'f32' | 'bf16' | 'int8'
    temperature: float = 1.0         # sampling defaults; per-request
    top_k: int = 0                   #   overrides ride submit()
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    attention_kernel: str = "ragged-xla"   # see ATTENTION_KERNELS
    attention_impl: Optional[str] = None   # deprecated alias: 'xla'|'pallas'
    #: speculative decoding (serving/spec.py SpecConfig: draft model +
    #: k). Greedy-only, unified tick only; the engine gains a second
    #: compiled site (the draft tick) and syncs each verify tick —
    #: acceptance decides the next tick's positions, so the deferred
    #: window cannot stay open across it (max_inflight is ignored).
    spec: Optional[SpecConfig] = None


@dataclass
class Request:
    rid: int
    prompt: np.ndarray               # current prompt (grows on preemption)
    max_new: int                     # tokens still wanted (shrinks on preempt)
    key: np.ndarray                  # uint32[2] sampling key (absolute-pos folds)
    out: List[int] = field(default_factory=list)
    done: bool = False
    #: prefill-group mode (ISSUE 13): stop after the prompt is fully
    #: prefilled and the FIRST token sampled — the request's KV pages
    #: are then exported to a decode-group engine instead of decoding
    #: here. Survives preemption (the requeued victim re-prefills and
    #: holds again).
    hold: bool = False
    submit_t: float = 0.0
    queue_t: float = 0.0             # (re)queue anchor: submit, or requeue
    preempts: int = 0                # times this request was preempted
    first_token_t: Optional[float] = None
    orig_prompt_len: int = 0         # for result accounting across preemption
    temperature: Optional[float] = None   # per-request sampling overrides
    top_k: Optional[int] = None           #   (None -> engine config default)
    top_p: Optional[float] = None
    #: cross-host trace id (ISSUE 14): stamped as a ``trace`` attr on
    #: every lifecycle event this engine emits for the request, and
    #: carried across the KV handoff so the decode rank's events join
    #: the same trace. None (local-only request) emits no attr.
    trace_id: Optional[str] = None
    #: abandoned without a result (ISSUE 17 orphan bookkeeping): set
    #: by ``cancel()`` when the mesh re-dispatched this gid elsewhere —
    #: ``done`` is True so the scheduler forgets it, but it must never
    #: surface as a served output (``run()``/coordinators skip it)
    canceled: bool = False


class _Inflight:
    __slots__ = ("tok", "meta")

    def __init__(self, tok, meta):
        self.tok = tok               # device int32 array
        self.meta = meta             # [(index_into_tok, slot, rid)]


#: one selected-but-not-yet-dispatched prompt chunk of the unified tick
_Chunk = Tuple[int, int, int, int, int]   # (slot, rid, start, end, t0)


def _copy_pages(kpool, vpool, src, dst):
    """Copy-on-write: duplicate page ``src`` into ``dst`` across all
    layers (one compiled program, pools donated)."""
    return (kpool.at[:, dst].set(kpool[:, src]),
            vpool.at[:, dst].set(vpool[:, src]))


def _copy_pages_q(kpool, vpool, kscale, vscale, src, dst):
    """COW for quantized pools: the donor page's per-head scales travel
    with its content (dequantizing the copied int8 values needs the
    SAME scales; the engine un-lists ``dst`` from the fresh-page reset
    so the next tick cannot zero them)."""
    return (kpool.at[:, dst].set(kpool[:, src]),
            vpool.at[:, dst].set(vpool[:, src]),
            kscale.at[:, dst].set(kscale[:, src]),
            vscale.at[:, dst].set(vscale[:, src]))


class ServingEngine:
    """Continuous-batching serving runtime for a dense ``GPT`` model.

    ::

        eng = ServingEngine(model, ServingConfig(num_slots=8))
        rid = eng.submit(prompt_ids, max_new_tokens=32)
        out = eng.run()[rid]          # np.int32 generated ids
    """

    def __init__(self, model, config: Optional[ServingConfig] = None):
        cfg = config or ServingConfig()
        mcfg = model.config
        if cfg.decode not in ("greedy", "sampling"):
            raise ValueError(f"unknown decode mode {cfg.decode!r}")
        if cfg.prefill_chunks_per_tick < 1:
            raise ValueError("prefill_chunks_per_tick must be >= 1")
        if cfg.scheduler not in SCHED_POLICIES:
            raise ValueError(
                f"unknown scheduler {cfg.scheduler!r}; expected one of "
                f"{SCHED_POLICIES}")
        kernel = cfg.attention_kernel
        if cfg.attention_impl is not None:
            if kernel != "ragged-xla":
                raise ValueError(
                    "attention_impl (deprecated) and attention_kernel "
                    "are both set — drop attention_impl")
            # pre-unification spelling: impl named only the attention
            # implementation, the dispatch structure was fixed
            kernel = {"xla": "ragged-xla",
                      "pallas": "ragged-pallas"}.get(cfg.attention_impl)
            if kernel is None:
                raise ValueError(
                    f"unknown attention impl {cfg.attention_impl!r}")
        if kernel not in ATTENTION_KERNELS:
            raise ValueError(
                f"unknown attention kernel {kernel!r}; expected one of "
                f"{ATTENTION_KERNELS}")
        self._spec = cfg.spec
        if self._spec is not None:
            if kernel == "legacy":
                raise ValueError(
                    "speculative decoding needs the unified mixed-row "
                    "tick; attention_kernel='legacy' has no verify row "
                    "path")
            if getattr(self._spec, "overlap", False) and \
                    cfg.decode != "sampling":
                raise ValueError(
                    "spec.overlap chains the next draft tick on the "
                    "sampled verify tick's device outputs; greedy spec "
                    "has no chained draft build — use decode='sampling'")
            if self._spec.k < 1:
                raise ValueError("spec.k must be >= 1")
        self._legacy = kernel == "legacy"
        if self._legacy and cfg.scheduler != "fifo":
            raise ValueError(
                "scheduler policies need the unified tick; "
                "attention_kernel='legacy' is the pre-unification "
                "bench baseline and keeps fifo chunk selection")
        self._impl = "pallas" if kernel.endswith("pallas") else "xla"
        self.attention_kernel = kernel
        # process index folded in: ids stay unique when rank-tagged
        # event streams from N processes are merged (ISSUE 13)
        self._eng_id = (_proc_index() << 20) | next(_ENGINE_SEQ)
        # {site: (jitted fn, arg avals)} captured at first dispatch —
        # record_program_stats() re-lowers from these for cost analysis
        self._program_args: Dict[str, tuple] = {}
        self.config = cfg
        self.model_config = mcfg
        self._stacked, self._other = model._decode_state()
        self._dtype = self._other["embeddings.wte.weight"].dtype
        # page-pool storage dtype (ISSUE 12): None follows the model
        kv_map = {None: self._dtype, "f32": jnp.float32,
                  "bf16": jnp.bfloat16, "int8": jnp.int8}
        if cfg.kv_dtype not in kv_map:
            raise ValueError(
                f"unknown kv_dtype {cfg.kv_dtype!r}; expected one of "
                "None (model dtype), 'f32', 'bf16', 'int8'")
        kv_jnp = jnp.dtype(kv_map[cfg.kv_dtype])
        if self._legacy and kv_jnp != jnp.dtype(self._dtype):
            raise ValueError(
                f"kv_dtype={cfg.kv_dtype!r} needs the unified tick; "
                "attention_kernel='legacy' is the pre-unification "
                "bench baseline and keeps the model-dtype pool")
        self._quantized = kv_jnp == jnp.dtype(jnp.int8)
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        ps = cfg.page_size
        pages_per_slot = cfg.pages_per_slot or -(-mcfg.max_seq_len // ps)
        num_pages = cfg.num_pages or cfg.num_slots * pages_per_slot + 1
        self.pool = PagePool(mcfg.num_layers, num_pages, ps, nh, hd,
                             cfg.num_slots, pages_per_slot,
                             dtype=kv_jnp,
                             prefix_cache=cfg.prefix_cache)
        self.prefill_chunk = int(cfg.prefill_chunk) or 2 * ps
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        b_slots = cfg.num_slots
        # chunk-selection + budget policy (ISSUE 15; serving/sched.py)
        # — host-side only: picks which slot opens the next prefill
        # chunk and how many chunks this tick selects, never what any
        # compiled program looks like
        self._sched = ChunkScheduler(
            cfg.scheduler, b_slots, self.pool.slot_capacity,
            self.prefill_chunk, cfg.prefill_chunks_per_tick)
        # host scheduling state (never reads device data)
        self._slot_rid: List[Optional[int]] = [None] * b_slots
        self._slot_len = np.zeros(b_slots, np.int32)      # tokens in cache
        self._slot_prompt = np.zeros(b_slots, np.int32)   # current prompt len
        self._slot_dispatched = np.zeros(b_slots, np.int64)  # tokens emitted
        self._slot_admit_seq = np.zeros(b_slots, np.int64)
        self._slot_admit_t = np.zeros(b_slots, np.float64)
        #: latch: this admission cycle still owes its chunk-wait
        #: sample (recorded at the first chunk that actually OPENS —
        #: a selection whose page acquisition freed the slot opened
        #: nothing and must not count as service)
        self._slot_wait_due = [False] * b_slots
        #: per-ENGINE admission->first-chunk waits (bounded recent
        #: window) next to the registry-global serving/chunk_wait_ms
        #: histogram — co-resident engines (e.g. a policy matrix)
        #: share the registry, so per-engine evidence needs its own
        #: samples
        self.chunk_waits_ms: deque = deque(maxlen=1024)
        self._slot_looked_up = [False] * b_slots
        self._admit_seq = 0
        self._queue: deque[Request] = deque()
        self._requests: Dict[int, Request] = {}
        self._next_rid = 0
        self._inflight: deque[_Inflight] = deque()
        #: held requests whose first token has materialized — ready for
        #: export_held() (disaggregated prefill group, ISSUE 13)
        self._held_ready: set = set()
        self._import_fn = None       # lazy jitted KV-import scatter
        self._export_fn = None       # lazy jitted KV-export gather
        self.max_inflight_seen = 0
        # device state
        self._last_tok = jnp.zeros((b_slots,), jnp.int32)
        self._keys = np.zeros((b_slots, 2), np.uint32)
        # per-slot sampling params (fixed-shape tick arguments)
        self._temps = np.full(b_slots, cfg.temperature, np.float32)
        self._topks = np.full(b_slots, cfg.top_k, np.int32)
        self._topps = np.full(b_slots, cfg.top_p, np.float32)
        self._base_key = np.asarray(jax.random.PRNGKey(cfg.seed))
        # compiled programs. Unified (default): ONE mixed-row tick site
        # serving decodes AND prefill chunks, asserted single-trace.
        # Legacy: the pre-unification pair (decode tick + suffix-prefill
        # chunk program), kept for the dispatch-collapse benchmark.
        self._tick_site = _recompile.unique_site("serving.tick")
        if self._legacy:
            self._prefill_site = _recompile.unique_site("serving.prefill")
            self._tick = jax.jit(self._make_legacy_tick(),
                                 donate_argnums=(2, 3))
            self._prefill = jax.jit(self._make_prefill_chunk(),
                                    donate_argnums=(2, 3))
        elif self._spec is not None:
            from .spec import DraftRunner, make_spec_tick

            dcfg = self._spec.draft_model.config
            if dcfg.vocab_size != mcfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size {dcfg.vocab_size} != target "
                    f"{mcfg.vocab_size}: acceptance compares token ids")
            if dcfg.max_seq_len < mcfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {dcfg.max_seq_len} must cover "
                    f"the target's {mcfg.max_seq_len}")
            self._spec_k = int(self._spec.k)
            #: adaptive per-slot draft depth (ISSUE 15; sched.py):
            #: accept-rate EWMA -> depth in the compiled [0, k] range
            #: the verify tick already supports via row_len. None =
            #: static k (the PR 9 behavior).
            self._spec_ctl = (
                SpecKController(b_slots, self._spec_k,
                                self._spec.ewma_alpha,
                                getattr(self._spec,
                                        "reprobe_every", 0))
                if self._spec.adaptive else None)
            #: per-tick cache of tick_depth() results — the probe
            #: state machine advances once per slot per tick even
            #: though depth is consulted at both the draft-feed and
            #: the ks-clamp points
            self._spec_tick_depth: Dict[int, int] = {}
            #: sampled spec decoding (ISSUE 20): the verify tick runs
            #: the rejection-sampling acceptance kernel instead of the
            #: greedy longest-argmax-prefix rule
            self._spec_sampling = cfg.decode == "sampling"
            #: hide the host-side accept/absorb sync under the NEXT
            #: draft tick: dispatch the chained draft build against the
            #: verify tick's still-on-device outputs before
            #: materializing them (sampling only)
            self._spec_overlap = bool(getattr(self._spec, "overlap",
                                              False))
            #: pending chained draft state: dict with device drafts /
            #: probs plus host validity mask, or None when no chained
            #: tick is in flight
            self._spec_pend: Optional[dict] = None
            self._draft = DraftRunner(
                self._spec.draft_model, b_slots,
                self.pool.slot_capacity, self._spec_k,
                self.prefill_chunk, self.pool,
                sampling=self._spec_sampling)
            #: per-admission-cycle lifecycle-event latches
            self._spec_started = [False] * b_slots
            self._spec_verifying = [False] * b_slots
            self._zero_drafts = np.zeros(b_slots * self._spec_k,
                                         np.int32)
            if self._spec_sampling:
                # draft-probs placeholder for ticks where no slot was
                # offered drafts (n_draft == 0 everywhere => unread)
                self._zero_probs = np.zeros(
                    (b_slots, self._spec_k, mcfg.vocab_size),
                    np.float32)
            self._tick = jax.jit(
                make_spec_tick(mcfg, b_slots, self._spec_k,
                               self.prefill_chunk, self._impl,
                               self._tick_site,
                               quantized=self._quantized,
                               sampling=self._spec_sampling),
                donate_argnums=(2, 3, 4, 5) if self._quantized
                else (2, 3))
        else:
            self._tick = jax.jit(self._make_unified_tick(),
                                 donate_argnums=(2, 3, 4, 5)
                                 if self._quantized else (2, 3))
        if self._quantized:
            self._copy = jax.jit(_copy_pages_q,
                                 donate_argnums=(0, 1, 2, 3))
            # fixed-size fresh-page reset vector folded into every tick
            # (paged_cache.take_fresh): sized past the worst case one
            # scheduler step can allocate — decode growth (<= 1 page
            # per slot), speculation growth, and the selected chunks'
            # pages — so the eager-reset overflow path never triggers
            # in normal operation (it stays correct if it does).
            # +1 covers draft-page rewind churn: freed draft pages
            # re-enter the fresh list via the allocator's on_zero hook
            spec_extra = (self._spec_k // ps + 3) \
                if self._spec is not None else 0
            self._fresh_cap = (
                b_slots * (1 + spec_extra)
                + cfg.prefill_chunks_per_tick
                * (self.prefill_chunk // ps + 2) + 8)
        else:
            self._copy = jax.jit(_copy_pages, donate_argnums=(0, 1))

    @property
    def compiled_sites(self) -> Tuple[str, ...]:
        """Recompile-telemetry site names of this engine's hot-path
        dispatch programs — the unified engine has exactly ONE (the
        mixed-row tick); a spec-decoding engine has exactly TWO (the
        draft tick + the verify/mixed tick); only the legacy mode has
        a separate prefill program. Tests assert this, so silently
        re-growing a dispatch site is a visible regression."""
        if self._legacy:
            return (self._tick_site, self._prefill_site)
        if self._spec is not None:
            return (self._tick_site, self._draft.site)
        return (self._tick_site,)

    def _emit(self, kind: str, rid: int, **attrs) -> None:
        req = self._requests.get(rid)
        if req is not None and req.trace_id is not None:
            attrs.setdefault("trace", req.trace_id)
        _events.emit(kind, rid=rid, eng=self._eng_id, **attrs)

    def _pool_args(self) -> tuple:
        """The pool's device-state args for a tick dispatch (shared by
        the unified and spec sites). Order matters in int8 mode:
        ``take_fresh`` runs BEFORE the scale arrays are captured —
        its overflow path eagerly rewrites them, and capturing first
        would dispatch the stale arrays and then clobber the reset
        with the tick's output."""
        if not self._quantized:
            return (self.pool.k, self.pool.v)
        fresh = self.pool.take_fresh(self._fresh_cap)
        return (self.pool.k, self.pool.v, self.pool.k_scale,
                self.pool.v_scale, fresh)

    def _store_pools(self, outs: tuple) -> tuple:
        """Store a tick's donated pool outputs back on the pool;
        returns the remaining (per-mode) outputs."""
        if self._quantized:
            (self.pool.k, self.pool.v, self.pool.k_scale,
             self.pool.v_scale) = outs[:4]
            return outs[4:]
        self.pool.k, self.pool.v = outs[:2]
        return outs[2:]

    def _note_avals(self, site: str, fn, args: tuple) -> None:
        """Remember a dispatch site's argument avals (shape/dtype only
        — captured BEFORE dispatch, since donation invalidates the pool
        buffers) the first time it dispatches."""
        if site in self._program_args:
            return

        def aval(a):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return jax.ShapeDtypeStruct(np.shape(a), a.dtype)
            x = np.asarray(a)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        self._program_args[site] = (
            fn, jax.tree_util.tree_map(aval, args))

    def record_program_stats(self) -> Dict[str, dict]:
        """Fold compile wall-time + ``cost_analysis()`` FLOPs/bytes of
        every hot-path program that has dispatched at least once into
        the profiler's program inventory (``xla_stats``), keyed by
        ``compiled_sites`` names. Re-lowers from the captured avals and
        compiles OFF the hot path (a diagnostic compile, suppressed in
        retrace telemetry; on a warm XLA cache it times the cache hit).
        Returns {site: stats-dict}."""
        from ..profiler import xla_stats as _xla

        out = {}
        for site, (fn, avals) in sorted(self._program_args.items()):
            out[site] = _xla.record_lowered(
                site, fn.lower(*avals)).to_dict()
        return out

    @contextmanager
    def trace_window(self, log_dir: Optional[str] = None,
                     peak_flops: Optional[float] = None):
        """Capture a parsed device-trace window over the ticks driven
        inside the block (ISSUE 11)::

            with eng.trace_window() as cap:
                for _ in range(8):
                    eng.step()
                eng.drain(0)          # sync before the trace stops
            cap.summary               # per-tick device timeline

        Records the hot-path programs first (``record_program_stats``
        — registers the HLO-module -> site join keys and cost-analysis
        FLOPs, so slices attribute to ``serving.tick#N`` and the MFU
        ledger has its numerator), then wraps the block in a
        ``device_trace.capture`` whose ``steps`` is set to the MEASURED
        tick count (the ``serving/ticks`` counter delta), so the
        summary's per-step rows read per-tick. Callers must drain
        in-flight ticks before the block ends or the trailing device
        work is cut off the timeline."""
        from ..profiler import device_trace as _dtrace

        self.record_program_stats()
        t0 = _registry().counter("serving/ticks").value
        cap = _dtrace.capture(log_dir=log_dir, peak_flops=peak_flops,
                              label=f"serving.eng{self._eng_id}")
        with cap:
            yield cap
            cap.steps = int(
                _registry().counter("serving/ticks").value - t0) or None

    def latency_stats(self, window_s: Optional[float] = None) -> dict:
        """Rolling-window TTFT/TPOT p50/p90/p95/p99 over requests
        finished in the last ``window_s`` seconds (None: everything
        still in the event ring). Reads the process-global event log —
        finished requests of OTHER live engines are included; use
        ``profiler.latency_table()`` rows (grouped by ``eng``) to
        split."""
        return _events.request_latency_stats(window_s=window_s)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               key: Optional[np.ndarray] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               hold_after_prefill: bool = False,
               trace_id: Optional[str] = None) -> int:
        """Queue one request. ``temperature``/``top_k``/``top_p``
        override the engine-global sampling params for this request
        only (ignored under greedy decode). Returns its request id.
        ``trace_id`` (ISSUE 14) tags every event of this request with
        a cross-host ``trace`` attr and rides any KV handoff.

        ``hold_after_prefill`` puts the request in prefill-group mode
        (ISSUE 13): the engine prefills the prompt (chunked, prefix-
        cached, preemptible — all the normal machinery) and samples the
        FIRST token, then parks the slot instead of decoding; the
        coordinator exports the KV pages (``export_held``) to a decode
        engine and releases the slot (``release_exported``). Held slots
        never ride decode ticks, so a prefill-group engine's tick only
        ever carries chunk rows."""
        p = np.asarray(prompt_ids, np.int32).reshape(-1)
        t0 = p.shape[0]
        cap = self.pool.slot_capacity
        if t0 < 1 or max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        if t0 + max_new_tokens - 1 > cap:
            raise ValueError(
                f"prompt {t0} + {max_new_tokens} new tokens needs "
                f"{t0 + max_new_tokens - 1} cache positions; slot capacity "
                f"is {cap} (pages_per_slot * page_size) — raise "
                "pages_per_slot or page_size")
        if self.pool.pages_for(t0 + max_new_tokens - 1) > \
                self.pool.allocator.num_pages - 1:
            raise ValueError("request exceeds the whole page pool")
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = np.asarray(jax.random.fold_in(self._base_key, rid))
        now = time.perf_counter()
        req = Request(rid=rid, prompt=p, max_new=int(max_new_tokens),
                      key=np.asarray(key, np.uint32),
                      submit_t=now, queue_t=now, orig_prompt_len=t0,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      hold=bool(hold_after_prefill),
                      trace_id=trace_id)
        self._requests[rid] = req
        self._queue.append(req)
        # the prefix-hit-rate denominator (ISSUE 16 mesh rollup:
        # prefix_hit_tokens / prompt_tokens)
        _registry().counter("serving/prompt_tokens").add(t0)
        self._emit("submit", rid, prompt_tokens=t0,
                   max_new=int(max_new_tokens))
        return rid

    def step(self) -> bool:
        """One scheduler iteration: bound the in-flight window, admit
        into free slots, select up to ``prefill_chunks_per_tick``
        prompt chunks, grow pages (preempting on exhaustion), dispatch
        ONE unified tick carrying the selected chunks plus every
        resident decode (legacy mode: the old chunk-then-tick dispatch
        pair). Returns whether any device work was dispatched."""
        self._sched.on_tick()
        self._drain(self.config.max_inflight)
        self._admit()
        if self._legacy:
            dispatched = self._prefill_chunks()
            self._grow_pages()
            dispatched = self._dispatch_legacy_tick() or dispatched
        elif self._spec is not None:
            chunks = self._collect_chunks()
            self._grow_pages()
            dispatched = self._dispatch_spec(chunks)
        else:
            chunks = self._collect_chunks()
            self._grow_pages()
            dispatched = self._dispatch_unified(chunks)
        reg = _registry()
        reg.gauge("serving/queue_depth").set(float(len(self._queue)))
        reg.gauge("serving/active_slots").set(
            float(sum(r is not None for r in self._slot_rid)))
        reg.gauge("serving/page_util").set(self.pool.allocator.utilization())
        return dispatched

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until every submitted request finished; returns
        {rid: generated ids np.int32[<=max_new]}."""
        t0 = time.perf_counter()
        tokens0 = self._tokens_done()
        while True:
            progressed = self.step()
            if not progressed:
                if self._inflight:
                    self._drain(0)
                    continue
                if all(r is None for r in self._slot_rid):
                    if not self._queue:
                        break
                    # every slot free, window empty, still can't admit
                    raise RuntimeError(
                        "serving queue stalled: page pool too small for "
                        "the queued prompt")
                raise RuntimeError(
                    "serving scheduler deadlock: resident requests but "
                    "nothing dispatchable")
        wall = max(time.perf_counter() - t0, 1e-9)
        done = self._tokens_done() - tokens0
        _registry().gauge("serving/tokens_per_sec").set(done / wall)
        return {rid: np.asarray(r.out, np.int32)
                for rid, r in self._requests.items()
                if r.done and not r.canceled}

    def drain(self, target: int = 0) -> None:
        """Materialize in-flight ticks until at most ``target`` remain."""
        self._drain(target)

    def idle(self) -> bool:
        """True when nothing is queued, resident, or in flight."""
        return (not self._queue and not self._inflight
                and all(r is None for r in self._slot_rid))

    def reset_results(self) -> None:
        """Forget finished requests (long-running host keeps memory flat)."""
        self._requests = {rid: r for rid, r in self._requests.items()
                          if not r.done}

    def cancel(self, rid: int, reason: str = "redispatch") -> bool:
        """Abandon a request wherever it stands — queued, resident
        (prefilling or decoding), or held-ready — freeing its slot and
        pages WITHOUT producing a result (ISSUE 17 orphan bookkeeping:
        when the mesh re-dispatches a gid away from this rank, the
        stale local work must be torn down or it would double-serve).
        Drains in-flight ticks first (a slot cannot be released under
        a tick that still carries its row), releases the slot/pages,
        marks the request done+canceled so the scheduler forgets it,
        and emits a ``cancel`` event. Returns False for an unknown or
        already-finished request (idempotent)."""
        req = self._requests.get(rid)
        if req is None or req.done:
            return False
        if any(r.rid == rid for r in self._queue):
            self._queue = type(self._queue)(
                r for r in self._queue if r.rid != rid)
        elif rid in self._slot_rid:
            # rare control-plane op: materializing the in-flight
            # window is the price of releasing a live slot safely
            self._drain(0)
            if rid in self._slot_rid:     # not finished by the drain
                slot = self._slot_rid.index(rid)
                self._spec_reset(slot)
                self._sched.note_release(slot)
                self.pool.release_slot(slot)
                self._slot_rid[slot] = None
                self._slot_len[slot] = 0
        if req.done:                      # the drain finished it for
            return False                  # real — a result exists
        self._held_ready.discard(rid)
        req.done = True
        req.canceled = True
        req.out = []
        _registry().counter("serving/requests_canceled").add(1)
        self._emit("cancel", rid, reason=reason)
        return True

    # ------------------------------------------------------------------
    # KV handoff (ISSUE 13, serving/disagg.py): a prefill-group engine
    # exports a held request's pages; a decode-group engine imports
    # them. Pages move as raw pool bytes — int8 pools hand off int8
    # values + their per-page scales, so the PR 12 byte cut applies to
    # the transfer for free. The import writer is a jitted fixed-shape
    # maintenance op like the COW copy (self._copy): it is NOT a
    # hot-path dispatch site, so ``compiled_sites`` is unchanged and
    # the decode group's tick keeps its decode-only fast path.
    # ------------------------------------------------------------------
    def held_ready(self) -> Tuple[int, ...]:
        """rids submitted with ``hold_after_prefill`` whose prompt is
        fully prefilled and first token materialized — exportable."""
        return tuple(sorted(self._held_ready))

    def export_held(self, rid: int) -> dict:
        """The KV-handoff payload of a held-ready request: its current
        prompt, remaining budget, sampling state, first token, and the
        raw page content (+ scales when quantized) for the
        ``ceil(t0 / page_size)`` pages holding the prompt's KV. The
        slot stays resident until ``release_exported`` — export is
        read-only, so a failed send can simply retry."""
        if rid not in self._held_ready:
            raise ValueError(f"request {rid} is not held-ready")
        t_span = time.perf_counter()
        req = self._requests[rid]
        slot = self._slot_rid.index(rid)
        pages = list(self.pool._held[slot])
        idx = np.asarray(pages, np.int32)
        t0 = int(self._slot_len[slot])
        assert t0 == req.prompt.shape[0], "held slot frontier != prompt"
        payload = {
            "prompt": np.asarray(req.prompt, np.int32),
            "orig_prompt_len": int(req.orig_prompt_len),
            "max_new": int(req.max_new),
            "first_token": int(req.out[0]),
            "key": np.asarray(req.key, np.uint32),
            "n_tokens": t0,
            "preempts": int(req.preempts),
            # the receiving pool must store the SAME representation —
            # int8 bytes dequantize only with their scales, and f32
            # bytes are garbage reinterpreted as int8
            "kv_dtype": str(np.dtype(self.pool.k.dtype)),
            "k": np.asarray(self.pool.k[:, idx]),
            "v": np.asarray(self.pool.v[:, idx]),
        }
        # per-request sampling overrides travel with the request (only
        # when set — absent keys mean "decode rank's engine defaults",
        # exactly like a local submit with None overrides)
        if req.temperature is not None:
            payload["temperature"] = float(req.temperature)
        if req.top_k is not None:
            payload["top_k"] = int(req.top_k)
        if req.top_p is not None:
            payload["top_p"] = float(req.top_p)
        if self._quantized:
            payload["k_scale"] = np.asarray(self.pool.k_scale[:, idx])
            payload["v_scale"] = np.asarray(self.pool.v_scale[:, idx])
        if req.trace_id is not None:
            # the cross-host join key rides the payload: the decode
            # rank's request (and all its events) joins this trace
            payload["trace_id"] = req.trace_id
        nbytes = sum(payload[k].nbytes for k in
                     ("k", "v") + (("k_scale", "v_scale")
                                   if self._quantized else ()))
        reg = _registry()
        reg.counter("serving/handoffs_out").add(1)
        reg.counter("serving/handoff_bytes_out").add(nbytes)
        self._emit("handoff_out", rid, slot=slot, tokens=t0,
                   pages=len(pages), bytes=nbytes,
                   ms=round((time.perf_counter() - t_span) * 1e3, 3))
        return payload

    def release_exported(self, rid: int) -> None:
        """Drop a held request after its payload shipped: publish the
        fully-written prompt pages into the local prefix index (an
        identical later prompt re-prefills for free — rank-local by
        design), release the slot, and mark the request done HERE (the
        decode group owns the visible finish)."""
        if rid not in self._held_ready:
            raise ValueError(f"request {rid} is not held-ready")
        req = self._requests[rid]
        slot = self._slot_rid.index(rid)
        self._insert_prefix(slot, req.prompt, int(self._slot_len[slot]))
        self._sched.note_release(slot)
        self.pool.release_slot(slot)
        self._slot_rid[slot] = None
        self._slot_len[slot] = 0
        self._held_ready.discard(rid)
        req.done = True

    def admit_prefilled(self, payload: dict) -> Optional[int]:
        """Decode-group admission of an exported payload: bind a free
        slot, allocate the prompt's pages, write the transferred KV
        (+ scales) into them, and seed the decode state exactly where a
        local prefill finisher would have left it (frontier at the
        prompt length, one token dispatched, ``last_tok`` = the first
        token) — so the next unified tick is an ordinary decode row and
        greedy output stays bitwise the single-host stream. Returns the
        local rid, or None when no slot/pages are free right now (the
        caller retries; imports never preempt residents — a transfer
        must not evict committed decode work)."""
        t_span = time.perf_counter()
        p = np.asarray(payload["prompt"], np.int32).reshape(-1)
        t0 = p.shape[0]
        max_new = int(payload["max_new"])
        first_tok = int(payload["first_token"])
        tid = payload.get("trace_id")
        tid = str(tid) if tid is not None else None
        src_dtype = payload.get("kv_dtype")
        if src_dtype is not None and \
                str(np.dtype(str(src_dtype))) != \
                str(np.dtype(self.pool.k.dtype)):
            raise ValueError(
                f"handoff payload carries {str(src_dtype)!r} KV pages "
                f"but this pool stores {np.dtype(self.pool.k.dtype)!s} "
                "— prefill and decode groups must serve the same "
                "kv_dtype (silently casting would corrupt the cache)")
        cap = self.pool.slot_capacity
        if t0 + max_new - 1 > cap:
            raise ValueError(
                f"handoff needs {t0 + max_new - 1} cache positions; "
                f"slot capacity is {cap}")
        free = [s for s, r in enumerate(self._slot_rid) if r is None]
        if not free:
            return None
        slot = free.pop()
        n_pages = self.pool.pages_for(t0)
        if n_pages != payload["k"].shape[1]:
            raise ValueError(
                f"payload carries {payload['k'].shape[1]} pages for a "
                f"{t0}-token prompt; expected {n_pages}")
        if not self.pool.grow_slot(slot, n_pages):
            return None
        rid = self._next_rid
        self._next_rid += 1
        now = time.perf_counter()
        req = Request(rid=rid, prompt=p, max_new=max_new,
                      key=np.asarray(payload["key"], np.uint32),
                      out=[first_tok], submit_t=now, queue_t=now,
                      orig_prompt_len=int(payload["orig_prompt_len"]),
                      preempts=int(payload.get("preempts", 0)),
                      trace_id=tid)
        req.first_token_t = now
        self._requests[rid] = req
        self._slot_rid[slot] = rid
        self._slot_len[slot] = t0
        self._slot_prompt[slot] = t0
        self._slot_dispatched[slot] = 1
        self._slot_looked_up[slot] = True     # no prefill owed here
        self._admit_seq += 1
        self._slot_admit_seq[slot] = self._admit_seq
        self._slot_admit_t[slot] = now
        self._slot_wait_due[slot] = False    # no chunk ever opens here
        self._spec_reset(slot)
        self._keys[slot] = req.key
        c = self.config
        self._temps[slot] = c.temperature if \
            payload.get("temperature") is None else payload["temperature"]
        self._topks[slot] = c.top_k if payload.get("top_k") is None \
            else payload["top_k"]
        self._topps[slot] = c.top_p if payload.get("top_p") is None \
            else payload["top_p"]
        self._write_imported_pages(slot, payload)
        self._last_tok = self._last_tok.at[slot].set(first_tok)
        nbytes = sum(payload[k].nbytes for k in
                     ("k", "v") + (("k_scale", "v_scale")
                                   if self._quantized else ()))
        reg = _registry()
        reg.counter("serving/handoffs_in").add(1)
        reg.counter("serving/handoff_bytes_in").add(nbytes)
        self._emit("handoff_in", rid, slot=slot, tokens=t0,
                   pages=n_pages, bytes=nbytes,
                   ms=round((time.perf_counter() - t_span) * 1e3, 3))
        # the transferred first token may already satisfy the stop
        # conditions — finish without ever decoding
        eos = self.config.eos_token_id
        if eos is not None and first_tok == eos:
            self._finish(slot, rid, reason="eos")
        elif len(req.out) >= req.max_new:
            self._finish(slot, rid, reason="max_new")
        return rid

    def _write_imported_pages(self, slot: int, payload: dict) -> None:
        self._write_pages(self.pool._held[slot], payload)

    def _write_pages(self, pages, payload: dict) -> None:
        """One fixed-shape jitted scatter (padded to ``pages_per_slot``
        with the null page, whose content is always masked and whose
        scale pad is 0 — the null-scale pin survives) so imports of any
        page count share one compiled program. ``pages`` is the
        explicit destination list: a slot's held pages for a request
        handoff, or freshly-allocated index pages for a migrated
        prefix chain (ISSUE 18) — both ride the SAME jitted writer."""
        pool = self.pool
        pps = pool.pages_per_slot
        n = len(pages)
        dst = np.zeros(pps, np.int32)
        dst[:n] = pages
        shape = (pool.num_layers, pps, pool.page_size, pool.num_heads,
                 pool.head_dim)
        kbuf = np.zeros(shape, pool.k.dtype)
        vbuf = np.zeros(shape, pool.v.dtype)
        kbuf[:, :n] = payload["k"]
        vbuf[:, :n] = payload["v"]
        if self._import_fn is None:
            if self._quantized:
                def imp(kpool, vpool, kscale, vscale, kp, vp, ks, vs,
                        d):
                    return (kpool.at[:, d].set(kp),
                            vpool.at[:, d].set(vp),
                            kscale.at[:, d].set(ks),
                            vscale.at[:, d].set(vs))

                self._import_fn = jax.jit(imp,
                                          donate_argnums=(0, 1, 2, 3))
            else:
                def imp(kpool, vpool, kp, vp, d):
                    return (kpool.at[:, d].set(kp),
                            vpool.at[:, d].set(vp))

                self._import_fn = jax.jit(imp, donate_argnums=(0, 1))
        with _quiet_donation():
            if self._quantized:
                sshape = (pool.num_layers, pps, pool.num_heads)
                ksbuf = np.zeros(sshape, np.float32)
                vsbuf = np.zeros(sshape, np.float32)
                ksbuf[:, :n] = payload["k_scale"]
                vsbuf[:, :n] = payload["v_scale"]
                (pool.k, pool.v, pool.k_scale, pool.v_scale) = \
                    self._import_fn(pool.k, pool.v, pool.k_scale,
                                    pool.v_scale, kbuf, vbuf, ksbuf,
                                    vsbuf, dst)
                # the scale rows were just written by the import — the
                # next tick's fresh-page reset must not zero them
                for pg in pages:
                    pool.claim_fresh(int(pg))
            else:
                pool.k, pool.v = self._import_fn(pool.k, pool.v, kbuf,
                                                 vbuf, dst)

    # ------------------------------------------------------------------
    # hot prefix-chain migration (ISSUE 18). Host-side policy on the
    # SAME handoff representation as export_held/admit_prefilled: raw
    # page content (+ scales when quantized), never re-derived — so a
    # request admitted onto a migrated chain stays bitwise the stream
    # it would have produced where the chain originated. The jitted
    # page writer is shared with the request-handoff import; no new
    # compiled site.
    # ------------------------------------------------------------------
    def export_prefix_chain(self, tokens) -> Optional[dict]:
        """Payload replicating this rank's cached prefix chain of
        ``tokens`` (full indexed pages only, capped at one slot's
        worth — longer can't be aliased into any slot anyway), or None
        when nothing is cached — the chain may have been evicted since
        it was published, and a missed migration is a perf event, not
        an error."""
        pool = self.pool
        if pool.prefix is None:
            return None
        toks = np.asarray(tokens, np.int32).reshape(-1)
        pages, _hashes = pool.prefix.chain_pages(toks)
        pages = pages[:pool.pages_per_slot]
        if not pages:
            return None
        n = len(pages)
        n_tok = n * pool.page_size
        # one fixed-shape jitted gather (padded to pages_per_slot,
        # pad rows sliced off on the host) — chains of EVERY length
        # share one compiled program, so the first mid-serving
        # migration never pays a compile (the mirror of _write_pages)
        src = np.zeros(pool.pages_per_slot, np.int32)
        src[:n] = pages
        if self._export_fn is None:
            if self._quantized:
                def gat(kp, vp, ks, vs, s):
                    return kp[:, s], vp[:, s], ks[:, s], vs[:, s]
            else:
                def gat(kp, vp, s):
                    return kp[:, s], vp[:, s]
            self._export_fn = jax.jit(gat)
        payload = {
            "tokens": toks[:n_tok],
            "n_tokens": n_tok,
            "kv_dtype": str(np.dtype(pool.k.dtype)),
        }
        if self._quantized:
            k, v, ks, vs = self._export_fn(pool.k, pool.v,
                                           pool.k_scale, pool.v_scale,
                                           src)
            payload["k_scale"] = np.asarray(ks)[:, :n]
            payload["v_scale"] = np.asarray(vs)[:, :n]
        else:
            k, v = self._export_fn(pool.k, pool.v, src)
        payload["k"] = np.asarray(k)[:, :n]
        payload["v"] = np.asarray(v)[:, :n]
        return payload

    def import_prefix_chain(self, payload: dict) -> int:
        """Insert a migrated prefix chain into this rank's own trie
        under the normal refcount/COW rules: allocate fresh pages,
        write the transferred content (+ scales), index them, then
        drop the import's temporary reference — a chunk the local trie
        already held keeps the FIRST tenant's page (the import's copy
        of it returns straight to the pool). Returns the tokens newly
        indexed (0 = pool full right now, or nothing new — both
        perf-only). Raises ValueError on a payload this pool must not
        store (dtype/shape mismatch)."""
        pool = self.pool
        if pool.prefix is None:
            return 0
        toks = np.asarray(payload["tokens"], np.int32).reshape(-1)
        src_dtype = payload.get("kv_dtype")
        if src_dtype is not None and \
                str(np.dtype(str(src_dtype))) != \
                str(np.dtype(pool.k.dtype)):
            raise ValueError(
                f"migrated chain carries {str(src_dtype)!r} pages but "
                f"this pool stores {np.dtype(pool.k.dtype)!s}")
        n_pages = int(payload["k"].shape[1])
        ps = pool.page_size
        if n_pages < 1 or n_pages > pool.pages_per_slot or \
                n_pages * ps != toks.shape[0] or \
                payload["k"].shape != payload["v"].shape:
            raise ValueError("inconsistent migrated chain payload")
        if self._quantized and "k_scale" not in payload:
            raise ValueError("quantized chain without scales")
        # plain free-list alloc, deliberately NOT pool._alloc: a
        # speculative import must never evict committed local cache
        # entries to make room for itself
        pages = pool.allocator.alloc(n_pages)
        if pages is None:
            return 0                 # no room: drop
        self._write_pages(pages, payload)
        new = pool.prefix.insert(toks, pages)
        # drop the import's temporary refcount: newly-indexed pages
        # stay at 1 (index-held); duplicates of already-cached chunks
        # hit 0 and return to the pool (their scales re-queue for
        # reset via the allocator's on_zero hook)
        pool.allocator.free(pages)
        kept = [p for p in pages if pool.allocator.refcount(p) > 0]
        pool.migrated_pages.update(kept)
        return new * ps

    def _tokens_done(self) -> int:
        return sum(len(r.out) for r in self._requests.values())

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _drain(self, target: int) -> None:
        """Materialize in-flight ticks oldest-first until at most
        ``target`` remain. The ONLY place device data reaches the host."""
        while len(self._inflight) > target:
            ent = self._inflight.popleft()
            toks = np.asarray(ent.tok)
            _registry().counter("serving/token_syncs").add(1)
            now = time.perf_counter()
            for idx, slot, rid in ent.meta:
                req = self._requests[rid]
                if req.done:
                    continue        # EOS discovered while this was in flight
                tok = int(toks[idx])
                req.out.append(tok)
                _registry().counter("serving/tokens_generated").add(1)
                if req.first_token_t is None:
                    req.first_token_t = now
                    _registry().histogram("serving/ttft_ms").observe(
                        (now - req.submit_t) * 1000.0)
                    self._emit("first_token", rid, slot=slot)
                if req.hold:
                    # prefill-group mode: the first token is the LAST
                    # thing this engine computes for the request — park
                    # it for export; eos/max_new stops are the decode
                    # group's business (export_held ships the token)
                    self._held_ready.add(rid)
                    continue
                eos = self.config.eos_token_id
                # max_new counts tokens wanted since the LAST (re)queue —
                # preemption moved earlier output into the prompt and
                # shrank max_new to the remainder
                if eos is not None and tok == eos:
                    self._finish(slot, rid, reason="eos")
                elif len(req.out) >= req.max_new:
                    self._finish(slot, rid, reason="max_new")

    def _insert_prefix(self, slot: int, tokens: np.ndarray,
                       written: int) -> None:
        """Register ``slot``'s fully-written pages (KV for
        ``tokens[:written]``) in the prefix index."""
        if self.pool.prefix is None:
            return
        n_full = min(written, tokens.shape[0]) // self.pool.page_size
        if n_full:
            self.pool.prefix.insert(
                tokens[:n_full * self.pool.page_size],
                [int(p) for p in self.pool.tables[slot, :n_full]])

    def _spec_reset(self, slot: int) -> None:
        """Invalidate the slot's draft state (admission, finish,
        preemption): the next tenant's draft cache re-feeds from 0."""
        if self._spec is None:
            return
        self._draft.reset_slot(slot)
        if self._spec_pend is not None:
            # a chained draft tick built on this tenant's frontier is
            # meaningless for the next one
            self._spec_pend["valid"][slot] = False
        if self._spec_ctl is not None:
            self._spec_ctl.reset(slot)
        self._spec_started[slot] = False
        self._spec_verifying[slot] = False

    def _finish(self, slot: int, rid: int,
                reason: str = "max_new") -> None:
        req = self._requests[rid]
        req.done = True
        self._held_ready.discard(rid)
        if self._slot_rid[slot] == rid:
            self._spec_reset(slot)
            self._sched.note_release(slot)
            # cache the finished sequence's pages (prompt AND generated
            # full pages) before release: an identical follow-up
            # conversation prefix becomes a prefix hit
            seq = np.concatenate(
                [req.prompt, np.asarray(req.out, np.int32)])
            self._insert_prefix(slot, seq, int(self._slot_len[slot]))
            self.pool.release_slot(slot)
            self._slot_rid[slot] = None
            self._slot_len[slot] = 0
        # fold the preemption-era prefix back into the result
        extra = req.prompt[req.orig_prompt_len:]
        if extra.size:
            req.out = list(extra) + req.out
        _registry().counter("serving/requests_finished").add(1)
        now = time.perf_counter()
        tokens = len(req.out)
        ttft = tpot = None
        if req.first_token_t is not None:
            ttft = (req.first_token_t - req.submit_t) * 1000.0
            tpot = (now - req.first_token_t) * 1000.0 / max(tokens - 1, 1)
            # budget-shaping telemetry (sched.py): O(1) per finish
            self._sched.note_finish(ttft, tpot)
            # the SAME per-finish value the finish event carries, as a
            # mergeable sketch — the live plane's mesh TPOT percentiles
            # therefore agree with the offline merger's event-derived
            # ones up to the sketch's stated rel_err (ISSUE 16)
            _registry().histogram("serving/tpot_ms").observe(tpot)
        self._emit("finish", rid, tokens=tokens, reason=reason,
                   preempts=req.preempts,
                   ttft_ms=None if ttft is None else round(ttft, 3),
                   tpot_ms=None if tpot is None else round(tpot, 3))

    def _admit(self) -> None:
        """Move queued requests into free slots. Page allocation is
        deferred to the per-chunk prefill path (so the prefix lookup
        runs as late as possible — an identical prompt admitted a few
        ticks later sees the first tenant's pages already cached)."""
        free = [s for s, r in enumerate(self._slot_rid) if r is None]
        while self._queue and free:
            req = self._queue.popleft()
            slot = free.pop()
            self._slot_rid[slot] = req.rid
            self._slot_len[slot] = 0
            self._slot_prompt[slot] = req.prompt.shape[0]
            self._slot_dispatched[slot] = 0
            self._slot_looked_up[slot] = False
            self._spec_reset(slot)
            self._admit_seq += 1
            self._slot_admit_seq[slot] = self._admit_seq
            self._slot_admit_t[slot] = time.perf_counter()
            self._slot_wait_due[slot] = True
            self._sched.note_admit(slot)
            self._emit("admit", req.rid, slot=slot)
            self._keys[slot] = req.key
            c = self.config
            self._temps[slot] = (c.temperature if req.temperature is None
                                 else req.temperature)
            self._topks[slot] = c.top_k if req.top_k is None else req.top_k
            self._topps[slot] = c.top_p if req.top_p is None else req.top_p

    # ------------------------------------------------------------------
    # chunk selection + prefix cache (shared by both engine modes)
    # ------------------------------------------------------------------
    def _next_prefill_slot(self, pend: Dict[int, int]) -> Optional[int]:
        """The slot that opens the next prefill chunk, per the
        configured policy (``ServingConfig.scheduler``; sched.py).
        Under the default ``fifo`` this is the oldest-admitted pending
        slot — completing one request's prefill start-to-finish both
        minimizes its TTFT and publishes its pages before the next
        identical prompt looks them up; ``sjf``/``aged-sjf`` order by
        remaining prefill tokens (with deadline aging). ``pend``
        overlays chunk ends selected earlier in the same tick."""
        cands = []
        for s, rid in enumerate(self._slot_rid):
            if rid is None:
                continue
            frontier = pend.get(s, int(self._slot_len[s]))
            remaining = int(self._slot_prompt[s]) - frontier
            if remaining > 0:
                cands.append((s, int(self._slot_admit_seq[s]),
                              remaining))
        return self._sched.pick(cands)

    def _lookup_prefix(self, slot: int, req: Request) -> None:
        """Alias the longest cached page-aligned prefix of the prompt
        into ``slot`` (plus one copy-on-write page when the prompt
        diverges from a cached chunk mid-page) and start prefill at the
        first uncached position."""
        if self.pool.prefix is None:
            return
        full_pages, partial = self.pool.prefix.lookup(req.prompt)
        _registry().counter("serving/prefix_lookups").add(1)
        hit = 0
        remote = 0
        if full_pages:
            self.pool.share_into_slot(slot, full_pages)
            hit = len(full_pages) * self.pool.page_size
            if self.pool.migrated_pages:
                # cross-rank economy evidence (ISSUE 18): tokens served
                # off pages that arrived via chain migration — this
                # rank never prefilled them
                remote = sum(1 for p in full_pages
                             if p in self.pool.migrated_pages) \
                    * self.pool.page_size
        if partial is not None:
            src, lcp = partial
            # pin the donor page: the grow below may evict unreferenced
            # cached pages — src must not be reclaimed (or handed back
            # as the destination) mid-copy
            self.pool.allocator.share([src])
            try:
                if self.pool.grow_slot(slot, 1):
                    dst = self.pool.tables[slot,
                                           self.pool.slot_pages(slot) - 1]
                    with _quiet_donation():
                        if self._quantized:
                            # scales travel with the page; un-list dst
                            # from the fresh reset or the next tick
                            # would zero the copied scales
                            (self.pool.k, self.pool.v,
                             self.pool.k_scale, self.pool.v_scale) = \
                                self._copy(
                                    self.pool.k, self.pool.v,
                                    self.pool.k_scale,
                                    self.pool.v_scale,
                                    np.int32(src), np.int32(dst))
                            self.pool.claim_fresh(int(dst))
                        else:
                            self.pool.k, self.pool.v = self._copy(
                                self.pool.k, self.pool.v,
                                np.int32(src), np.int32(dst))
                    hit += lcp
                    _registry().counter("cache_share/cow_copies").add(1)
                    self._emit("cow_copy", req.rid, slot=slot, tokens=lcp)
            finally:
                self.pool.allocator.free([src])
        self._slot_len[slot] = hit
        if hit:
            _registry().counter("serving/prefix_hit_tokens").add(hit)
            if remote:
                _registry().counter(
                    "serving/prefix_hit_tokens_remote").add(remote)
            self._emit("prefix_hit", req.rid, slot=slot, tokens=hit,
                       remote_tokens=remote)

    def _observe_wait(self, req: "Request") -> None:
        """One wait sample per admission cycle. Fresh admissions anchor
        at submit (scheduler delay); requeued victims anchor at their
        preemption (preemption cost) — folding both into one
        submit-anchored histogram conflated the two (ISSUE 8
        satellite). Called at the cycle's first chunk open, or from
        ``_preempt_for`` when a cycle is preempted before it ever
        opened one — so qw count == requests and rw count ==
        preemptions hold under every interleaving."""
        wait_ms = (time.perf_counter() - req.queue_t) * 1000.0
        name = "serving/requeue_wait_ms" if req.preempts \
            else "serving/prefill_queue_wait_ms"
        _registry().histogram(name).observe(wait_ms)

    def _open_chunk(self, s: int,
                    pend: Dict[int, int]) -> Optional[_Chunk]:
        """Run the slot's first-chunk prefix lookup if due, then size
        the next prompt chunk and acquire its pages. Returns the chunk
        descriptor, or None when the slot was freed along the way
        (finished in the drain, or became its own preemption victim)."""
        rid = self._slot_rid[s]
        req = self._requests[rid]
        if not self._slot_looked_up[s]:
            self._slot_looked_up[s] = True
            self._observe_wait(req)
            self._lookup_prefix(s, req)
        t0 = int(self._slot_prompt[s])
        start = pend.get(s, int(self._slot_len[s]))
        end = min(start + self.prefill_chunk, t0)
        need = self.pool.pages_for(end) - self.pool.slot_pages(s)
        if not self._acquire_pages(s, need):
            return None
        if self._slot_wait_due[s]:
            # admission -> FIRST chunk open, per admission cycle:
            # recorded only once the chunk actually opened (pages
            # acquired) — the direct evidence of what the selection
            # policy did to start-of-service latency (ISSUE 15);
            # cycles preempted before ever opening contribute none
            self._slot_wait_due[s] = False
            wait_ms = (time.perf_counter()
                       - self._slot_admit_t[s]) * 1000.0
            _registry().histogram("serving/chunk_wait_ms").observe(
                wait_ms)
            self.chunk_waits_ms.append(wait_ms)
        self._sched.note_open(s)
        return (s, rid, start, end, t0)

    def _collect_chunks(self) -> List[_Chunk]:
        """Select up to the policy's per-tick budget of prompt chunks
        and acquire their pages WITHOUT dispatching — the unified tick
        carries them as prefill rows. The budget is shaped by
        decode-stall telemetry (sched.py ``chunk_budget``) but capped
        at the compiled ``prefill_chunks_per_tick`` worst case, so the
        tick shape never retraces; fifo keeps the constant budget.
        ``_slot_len`` commits only at dispatch: page acquisition can
        preempt a slot whose chunk was already selected (the chunk is
        then dropped), and publishing a frontier the dropped chunk
        never wrote would poison the prefix index."""
        chunks: List[_Chunk] = []
        pend: Dict[int, int] = {}
        npf = self.config.prefill_chunks_per_tick
        budget = npf
        if self._sched.shape_budget:
            pending = sum(
                1 for s, rid in enumerate(self._slot_rid)
                if rid is not None
                and int(self._slot_len[s]) < self._slot_prompt[s])
            budget = min(npf, self._sched.chunk_budget(
                pending, len(self._ticking_slots()),
                len(self._queue)))
            if budget < npf and pending:
                _registry().counter("serving/budget_cuts").add(1)
        for _ in range(budget):
            s = self._next_prefill_slot(pend)
            if s is None:
                break
            chunk = self._open_chunk(s, pend)
            if chunk is None:
                # the selected slot was freed during page acquisition
                # (finished in the drain, or became its own preemption
                # victim) — it is no longer a candidate, so spend the
                # remaining budget on the next pick instead of
                # abandoning the tick's chunk service (the aged-sjf
                # starvation bound rests on pending slots getting at
                # least one open per tick whenever one CAN open)
                continue
            pend[s] = chunk[3]
            chunks.append(chunk)
        return chunks          # _dispatch_unified drops stale entries

    def _acquire_pages(self, s: int, need: int) -> bool:
        """Grow slot ``s`` by ``need`` pages, escalating: free list
        (+ prefix-cache LRU eviction inside ``grow_slot``) -> drain
        in-flight finishes -> preempt youngest-first. The ONE
        exhaustion-recovery path, shared by prefill chunks and decode
        growth. Returns False when ``s`` itself was freed along the way
        (finished in the drain, or became its own preemption victim);
        raises only in the can't-happen state where the pool cannot
        cover a request ``submit()`` already validated against it."""
        if need <= 0 or self.pool.grow_slot(s, need):
            return True
        # draft pages are strictly lower-value than target pages:
        # reclaim them (decayed slots first, then everyone) before
        # draining finishes or preempting a tenant (ISSUE 20)
        if self._reclaim_draft(all_slots=False) and \
                self.pool.grow_slot(s, need):
            return True
        self._drain(0)
        if self._slot_rid[s] is None:
            return False
        if self.pool.grow_slot(s, need):
            return True
        if self._reclaim_draft(all_slots=True) and \
                self.pool.grow_slot(s, need):
            return True
        if not any(x != s and self._slot_rid[x] is not None
                   for x in range(self.config.num_slots)):
            raise RuntimeError(
                "serving pool exhausted: cannot cover a resident "
                "request even with the prefix cache drained and no "
                "co-resident to preempt")
        self._preempt_for(s, need)
        return self._slot_rid[s] is not None

    def _reclaim_draft(self, all_slots: bool) -> int:
        """Return draft-KV pages to the pool under target-page
        pressure. ``all_slots=False`` releases only slots whose
        adaptive depth has decayed to 0 (they are not speculating
        anyway — this is the 'adaptive-k decay returns draft pages'
        arm); ``all_slots=True`` releases every draft cache (the slots
        fall back to plain decode and re-feed if pressure eases).
        Never touches target pages. Returns pages freed."""
        if self._spec is None:
            return 0
        freed = 0
        for s in range(self.config.num_slots):
            if self._draft.aux.slot_pages(s) == 0:
                continue
            decayed = (self._spec_ctl is not None
                       and self._spec_ctl.depth(s) == 0)
            if all_slots or decayed:
                freed += self._draft.release_pages(s)
                if self._spec_pend is not None:
                    self._spec_pend["valid"][s] = False
        if freed:
            _registry().counter(
                "serving/spec_draft_pages_reclaimed").add(freed)
        return freed

    # ------------------------------------------------------------------
    # decode scheduling
    # ------------------------------------------------------------------
    def _ticking_slots(self) -> List[int]:
        """Slots that should advance this tick: resident, prefill
        complete, not finished, and with emissions still owed. A slot
        whose final token is already dispatched stops ticking
        immediately (max-token stop is host-deterministic); EOS stops
        lag by <= max_inflight ticks."""
        out = []
        for s, rid in enumerate(self._slot_rid):
            if rid is None:
                continue
            req = self._requests[rid]
            if req.hold:
                continue    # held slots stop at the prefill finisher's
            if not req.done and \
                    1 <= self._slot_dispatched[s] < req.max_new:
                out.append(s)
        return out

    def _grow_pages(self) -> None:
        for s in self._ticking_slots():
            if self._slot_rid[s] is None:
                continue            # freed by an earlier drain/preempt
            need_page = int(self._slot_len[s]) // self.pool.page_size
            if need_page < self.pool.slot_pages(s):
                continue
            self._acquire_pages(s, 1)

    def _preempt_for(self, needy_slot: int, need: int) -> None:
        """Free ``need`` pages by requeueing the youngest resident
        request (its generated prefix becomes prompt, so no work is
        redone twice — and its fully-written pages go into the prefix
        index first, so the re-prefill is a prefix hit)."""
        live = [s for s in range(self.config.num_slots)
                if self._slot_rid[s] is not None]
        victim = max(live, key=lambda s: self._slot_admit_seq[s])
        rid = self._slot_rid[victim]
        req = self._requests[rid]
        # window was drained before preemption, so req.out is current
        self._emit("preempt", rid, slot=victim, generated=len(req.out))
        if not self._slot_looked_up[victim]:
            # this admission cycle never opened a chunk: its wait
            # sample ends here (by preemption, not prefill start) —
            # without it the cycle's bucket is silently short a sample
            self._observe_wait(req)
        req.prompt = np.concatenate(
            [req.prompt, np.asarray(req.out, np.int32)])
        req.max_new -= len(req.out)
        req.out = []
        req.preempts += 1
        # a held-ready victim loses its parked first token with the
        # preemption (it moved into the prompt); the requeued cycle
        # re-prefills and parks again
        self._held_ready.discard(rid)
        req.queue_t = time.perf_counter()
        self._insert_prefix(victim, req.prompt, int(self._slot_len[victim]))
        self._queue.appendleft(req)
        self._spec_reset(victim)
        self._sched.note_release(victim)
        self.pool.release_slot(victim)
        self._slot_rid[victim] = None
        self._slot_len[victim] = 0
        _registry().counter("serving/preemptions").add(1)
        self._emit("requeue", rid, prompt_tokens=int(req.prompt.shape[0]),
                   max_new=req.max_new)
        if victim != needy_slot and self._slot_rid[needy_slot] is not None:
            if not self.pool.grow_slot(needy_slot, need):
                self._preempt_for(needy_slot, need)

    # ------------------------------------------------------------------
    # unified dispatch: ONE program per scheduler step
    # ------------------------------------------------------------------
    def _dispatch_unified(self, chunks: List[_Chunk]) -> bool:
        """Assemble and dispatch the mixed-row tick: one decode row per
        slot (inactive slots write to the null page through their
        zeroed table rows, exactly like the pre-unification tick) plus
        one ``prefill_chunk``-token row block per selected chunk. A
        chunk whose slot lost its request between selection and here
        (decode growth preempted it) is dropped — its acquired pages
        were already released with the slot."""
        chunks = [c for c in chunks if self._slot_rid[c[0]] == c[1]]
        ticking = self._ticking_slots()
        if not ticking and not chunks:
            return False
        ns = self.config.num_slots
        w = self.prefill_chunk
        npf = self.config.prefill_chunks_per_tick
        nps = self.pool.pages_per_slot
        cap = self.pool.slot_capacity
        nt = ns + npf * w
        pf_toks = np.zeros(npf * w, np.int32)
        tok_pos = np.zeros(nt, np.int32)
        tok_limit = np.zeros(nt, np.int32)   # pad rows: limit 0 -> null page
        tok_pos[:ns] = self._slot_len
        tok_limit[:ns] = cap
        # ragged row metadata: ns decode rows, then npf chunk rows (pad
        # chunk rows keep an all-null table and attend one masked key)
        row_tab = np.zeros((ns + npf, nps), np.int32)
        row_tab[:ns] = self.pool.tables
        row_pos0 = np.zeros(ns + npf, np.int32)
        row_pos0[:ns] = self._slot_len
        row_len = np.ones(ns + npf, np.int32)
        sample_ix = np.zeros(ns, np.int32)
        sample_pos = np.zeros(ns, np.int32)
        emit = np.zeros(ns, bool)
        for s in ticking:
            sample_ix[s] = s
            sample_pos[s] = self._slot_len[s] + 1
            emit[s] = True
        finishers = []
        for c, (s, rid, start, end, t0) in enumerate(chunks):
            base = ns + c * w
            req = self._requests[rid]
            pf_toks[c * w:c * w + (end - start)] = req.prompt[start:end]
            tok_pos[base:base + w] = start + np.arange(w)
            tok_limit[base:base + w] = t0
            row_tab[ns + c] = self.pool.tables[s]
            row_pos0[ns + c] = start
            row_len[ns + c] = end - start
            # the slot's decode row must sit at the post-chunk frontier
            # (it garbage-writes there, overwritten by the next real
            # token — never at a position this tick's chunk covers)
            tok_pos[s] = end
            row_pos0[s] = end
            if end >= t0:
                finishers.append((s, rid))
                sample_ix[s] = base + (t0 - 1 - start)
                sample_pos[s] = t0
                emit[s] = True
        tail = (self._last_tok, pf_toks, tok_pos, tok_limit, row_tab,
                row_pos0, row_len, sample_ix, sample_pos, emit,
                np.bool_(len(chunks) > 0),
                np.ascontiguousarray(self._keys),
                np.ascontiguousarray(self._temps),
                np.ascontiguousarray(self._topks),
                np.ascontiguousarray(self._topps))
        args = (self._stacked, self._other) + self._pool_args() + tail
        self._note_avals(self._tick_site, self._tick, args)
        with _quiet_donation():
            tok, self._last_tok = self._store_pools(self._tick(*args))
        meta = [(s, s, self._slot_rid[s]) for s in ticking]
        meta += [(s, s, rid) for s, rid in finishers]
        if meta:
            # chunk-only ticks (no decodes, no finishers) emit nothing
            # worth syncing — queueing them would stall the host on a
            # token vector nobody reads once the window fills
            self._inflight.append(_Inflight(tok, meta))
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     len(self._inflight))
        for s in ticking:
            self._slot_len[s] += 1
            self._slot_dispatched[s] += 1
        for s, rid, start, end, t0 in chunks:
            self._slot_len[s] = end
            self._emit("chunk", rid, slot=s, start=start, end=end,
                       final=bool(end >= t0))
            if end >= t0:
                self._slot_dispatched[s] = 1
                _registry().counter("serving/prefills").add(1)
            # publish the pages this chunk completed (progressively: a
            # long shared prompt becomes hittable page-by-page)
            self._insert_prefix(s, self._requests[rid].prompt, end)
        reg = _registry()
        reg.counter("serving/ticks").add(1)
        if chunks:
            reg.counter("serving/prefill_chunks").add(len(chunks))
        reg.gauge("serving/decode_batch").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows").set(float(len(ticking)
                                                  + len(chunks)))
        reg.gauge("serving/mixed_rows_decode").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows_prefill").set(float(len(chunks)))
        return True

    def _make_unified_tick(self):
        """The ONE compiled hot-path program: every resident decode and
        every selected prefill chunk of a scheduler step, as ragged
        rows of a single ``gpt_ragged_apply`` forward. All metadata is
        fixed-shape (pad prefill rows ride with limit 0), so the
        program traces exactly once across any prefill/decode mix,
        admission order, or per-request sampling params. Decode token
        values come from the DEVICE-side ``last_tok`` (the deferred
        sync never materializes them on the host); the final chunk of
        a prompt emits its slot's first token via ``sample_ix``, and
        ``emit`` folds emitted tokens back into ``last_tok`` for the
        next tick."""
        mcfg = self.model_config
        site = self._tick_site
        impl = self._impl
        ns = self.config.num_slots
        w = self.prefill_chunk
        quantized = self._quantized

        from ..models.gpt import gpt_ragged_apply

        def core(stacked, other, pools, last_tok, pf_toks, tok_pos,
                 tok_limit, row_tab, row_pos0, row_len, sample_ix,
                 has_chunks):
            tokens = jnp.concatenate([last_tok, pf_toks])

            def run(pl_, toks_, pos_, lim_, tab_, p0_, len_):
                if quantized:
                    kp, vp, ks, vs = pl_
                    lg, kp, vp, ks, vs = gpt_ragged_apply(
                        mcfg, stacked, other, kp, vp, toks_, pos_,
                        lim_, tab_, p0_, len_, sample_ix,
                        decode_rows=ns, chunk_width=w, impl=impl,
                        kscale=ks, vscale=vs)
                    return lg, (kp, vp, ks, vs)
                kp, vp = pl_
                lg, kp, vp = gpt_ragged_apply(
                    mcfg, stacked, other, kp, vp, toks_, pos_, lim_,
                    tab_, p0_, len_, sample_ix, decode_rows=ns,
                    chunk_width=w, impl=impl)
                return lg, (kp, vp)

            # ONE program, data-dependent prefill piggyback: both
            # branches trace into this single executable (the site
            # still traces exactly once); at runtime a decode-only
            # tick takes the ns-token branch, so the prefill-row
            # capacity costs nothing while nothing is prefilling —
            # a fixed-shape program otherwise pays its worst-case mix
            # every tick, which on the XLA path is real FLOPs, not
            # skipped blocks.
            def mixed(pl_):
                lg, pl_ = run(pl_, tokens, tok_pos, tok_limit,
                              row_tab, row_pos0, row_len)
                return (lg,) + pl_

            def decode_only(pl_):
                lg, pl_ = run(pl_, tokens[:ns], tok_pos[:ns],
                              tok_limit[:ns], row_tab[:ns],
                              row_pos0[:ns], row_len[:ns])
                return (lg,) + pl_

            out = jax.lax.cond(has_chunks, mixed, decode_only, pools)
            return out[0], out[1:]

        if quantized:
            def tick(stacked, other, kpool, vpool, kscale, vscale,
                     fresh, last_tok, pf_toks, tok_pos, tok_limit,
                     row_tab, row_pos0, row_len, sample_ix, sample_pos,
                     emit, has_chunks, keys, temps, top_ks, top_ps):
                _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                      last_tok)
                # recycled pages restart their running-max scale at 0
                # (fresh pads with the null page, whose scale is 0)
                kscale = kscale.at[:, fresh].set(0.0)
                vscale = vscale.at[:, fresh].set(0.0)
                logits, (kpool, vpool, kscale, vscale) = core(
                    stacked, other, (kpool, vpool, kscale, vscale),
                    last_tok, pf_toks, tok_pos, tok_limit, row_tab,
                    row_pos0, row_len, sample_ix, has_chunks)
                nxt = self._sample_tok(logits, keys, sample_pos, temps,
                                       top_ks, top_ps)
                new_last = jnp.where(emit, nxt, last_tok)
                return kpool, vpool, kscale, vscale, nxt, new_last
        else:
            def tick(stacked, other, kpool, vpool, last_tok, pf_toks,
                     tok_pos, tok_limit, row_tab, row_pos0, row_len,
                     sample_ix, sample_pos, emit, has_chunks, keys,
                     temps, top_ks, top_ps):
                _recompile.mark_trace(site, kpool, row_tab, tok_pos,
                                      last_tok)
                logits, (kpool, vpool) = core(
                    stacked, other, (kpool, vpool), last_tok, pf_toks,
                    tok_pos, tok_limit, row_tab, row_pos0, row_len,
                    sample_ix, has_chunks)
                nxt = self._sample_tok(logits, keys, sample_pos, temps,
                                       top_ks, top_ps)
                new_last = jnp.where(emit, nxt, last_tok)
                return kpool, vpool, nxt, new_last

        return tick

    # ------------------------------------------------------------------
    # speculative decoding (ServingConfig.spec; serving/spec.py): the
    # draft tick runs k tokens ahead per caught-up slot, then ONE
    # verify/mixed tick scores every slot's (1+k)-token row through
    # the same ragged program that carries the prefill chunks. Host
    # syncs each verify tick (acceptance decides the next tick's
    # positions); emitted tokens are always the TARGET's argmax
    # stream, so greedy output is bitwise non-speculative greedy.
    # ------------------------------------------------------------------
    def _dispatch_spec(self, chunks: List[_Chunk]) -> bool:
        """One spec scheduler step: (1) draft tick — parallel
        catch-up feed for behind slots + k draft steps for caught-up
        decoding slots (greedy argmax, or the slot's own sampling law
        under ``decode='sampling'``); slots with a valid CHAINED draft
        (overlap mode) skip this tick — their drafts were built by the
        previous step's chained dispatch; (2) per-slot speculation
        depth ``k_s`` (clamped by remaining budget, target page
        headroom AND draft page headroom — best-effort growth only,
        never preempting a co-resident to speculate deeper); (3) the
        verify/mixed tick (greedy longest-argmax-prefix acceptance, or
        the rejection-sampling kernel); (4) in overlap mode, dispatch
        the NEXT draft tick chained on the verify tick's still-on-
        device outputs — the host sync below then hides under its
        execution; (5) absorb — append the accepted prefix +
        correction token, rewind both frontiers past the rejected tail
        and return their pages, reconcile the chained tick's validity
        against what actually absorbed."""
        chunks = [c for c in chunks if self._slot_rid[c[0]] == c[1]]
        ticking = self._ticking_slots()
        if not ticking and not chunks:
            return False
        ns = self.config.num_slots
        k = self._spec_k
        w = self.prefill_chunk
        npf = self.config.prefill_chunks_per_tick
        nps = self.pool.pages_per_slot
        cap = self.pool.slot_capacity
        dr = self._draft
        reg = _registry()
        ticking_set = set(ticking)
        self._spec_tick_depth.clear()   # fresh probe decisions per tick
        sampling = self._spec_sampling
        pend = self._spec_pend

        # ---- draft tick: feed + generate (catch-up dispatch) ----
        feed_toks = np.zeros((ns, w), np.int32)
        feed_pos0 = np.zeros(ns, np.int32)
        feed_len = np.zeros(ns, np.int32)
        gen_tok = np.zeros(ns, np.int32)
        gen_pos = np.full(ns, cap, np.int32)   # cap = null-routed
        last_tok = np.zeros(ns, np.int32)
        gen_slots: List[int] = []
        chained: List[int] = []   # slots riding the pending chained tick
        any_feed = False
        for s, rid in enumerate(self._slot_rid):
            if rid is None:
                continue
            req = self._requests[rid]
            if s in ticking_set:
                last_tok[s] = req.out[-1]
            pend_ok = pend is not None and bool(pend["valid"][s])
            if self._spec_ctl is not None:
                # one probe-state advance per slot per tick (ISSUE 16
                # re-probe); the ks clamp below reuses the cached value
                self._spec_tick_depth[s] = \
                    self._spec_ctl.tick_depth(s)
                if self._spec_tick_depth[s] == 0:
                    # adaptive depth decayed to 0 (ISSUE 15): the slot
                    # rides as a plain decode row — feeding/drafting a
                    # cache nobody will verify is pure draft-tick cost,
                    # so the slot drops out of the draft tick entirely
                    # (a tick with nothing to feed and nobody
                    # generating skips the draft dispatch altogether,
                    # converging the engine to plain-engine cost
                    # structure). Reset on the next admission cycle —
                    # or a scheduled re-probe (SpecConfig.
                    # reprobe_every) — re-enables it.
                    if pend_ok:
                        pend["valid"][s] = False
                    continue
            if pend_ok:
                if s in ticking_set and \
                        req.max_new - len(req.out) >= 2:
                    # the chained draft tick already seeded past this
                    # frontier and drafted k tokens — no feed, no
                    # re-generate (the overlap payoff)
                    chained.append(s)
                    continue
                pend["valid"][s] = False
            behind = int(self._slot_len[s]) - int(dr.len[s])
            fed = 0
            if behind > 0:
                # catch the draft cache up toward the accepted
                # frontier: prompt tokens (admission / prefix hits the
                # draft never saw) and emitted tokens ride the same
                # chunk-shaped feed
                fed = min(behind, w)
                lo = int(dr.len[s])
                if not dr.grow_for(s, lo + fed):
                    # draft pages are best-effort: feed only as far as
                    # the pages already held reach
                    fed = max(0, min(fed, dr.held_tokens(s) - lo))
                if fed:
                    seq = np.concatenate(
                        [req.prompt, np.asarray(req.out, np.int32)])
                    feed_toks[s, :fed] = seq[lo:lo + fed]
                    feed_pos0[s] = lo
                    feed_len[s] = fed
                    any_feed = True
                    if not self._spec_started[s]:
                        self._spec_started[s] = True
                        self._emit("draft", rid, slot=s, pos=lo)
            if s in ticking_set and behind - fed == 0 and \
                    req.max_new - len(req.out) >= 2 and \
                    dr.grow_for(s, min(int(self._slot_len[s]) + k,
                                       cap)):
                gen_tok[s] = req.out[-1]
                gen_pos[s] = int(self._slot_len[s])
                gen_slots.append(s)
        draft_flat = self._zero_drafts
        dprobs_m = self._zero_probs if sampling else None
        drafts = dprobs = None
        if any_feed or gen_slots:
            dtab = np.ascontiguousarray(dr.aux.tables)
            if sampling:
                zc = np.zeros((ns, 1 + k), np.int32)
                zi = np.zeros(ns, np.int32)
                dargs = (dr.stacked, dr.other, dr.kc, dr.vc, dtab,
                         feed_toks, feed_pos0, feed_len, gen_tok,
                         gen_pos,
                         np.ascontiguousarray(self._keys),
                         np.ascontiguousarray(self._temps),
                         np.ascontiguousarray(self._topks),
                         np.ascontiguousarray(self._topps),
                         zc, zi, zi, np.zeros(ns, bool),
                         np.bool_(any_feed),
                         np.bool_(len(gen_slots) > 0))
                self._note_avals(dr.site, dr.tick, dargs)
                with _quiet_donation():
                    dr.kc, dr.vc, drafts, dprobs = dr.tick(*dargs)
                dprobs_m = dprobs
            else:
                dargs = (dr.stacked, dr.other, dr.kc, dr.vc, dtab,
                         feed_toks, feed_pos0, feed_len, gen_tok,
                         gen_pos, np.bool_(any_feed),
                         np.bool_(len(gen_slots) > 0))
                self._note_avals(dr.site, dr.tick, dargs)
                with _quiet_donation():
                    dr.kc, dr.vc, drafts = dr.tick(*dargs)
            draft_flat = drafts.reshape(-1)
            dr.len += feed_len
            reg.counter("serving/spec_draft_ticks").add(1)
            if any_feed:
                reg.counter("serving/spec_feed_tokens").add(
                    int(feed_len.sum()))
        if chained:
            # splice the pending chained drafts (device arrays from the
            # previous step's overlapped dispatch) over this tick's
            cm = np.zeros(ns, bool)
            cm[chained] = True
            cmj = jnp.asarray(cm)
            base_d = drafts if drafts is not None \
                else jnp.zeros((ns, k), jnp.int32)
            base_p = dprobs if dprobs is not None else self._zero_probs
            drafts = jnp.where(cmj[:, None], pend["drafts"], base_d)
            dprobs_m = jnp.where(cmj[:, None, None], pend["probs"],
                                 base_p)
            draft_flat = drafts.reshape(-1)
            reg.counter("serving/spec_chained_consumed").add(
                len(chained))

        # ---- per-slot speculation depth (host-deterministic) ----
        k_arr = np.zeros(ns, np.int32)
        for s in gen_slots + chained:
            rid = self._slot_rid[s]
            req = self._requests[rid]
            pos0 = int(self._slot_len[s])
            ks = min(k, req.max_new - len(req.out) - 1, cap - 1 - pos0)
            if self._spec_ctl is not None:
                # adaptive depth (ISSUE 15): the slot's accept-rate
                # EWMA picks a depth in the compiled [0, k] range —
                # a decayed slot rides as a plain decode row. The
                # cached tick_depth keeps a re-probe tick at depth 1
                # consistent between the feed loop and this clamp.
                ks = min(ks, self._spec_tick_depth.get(
                    s, self._spec_ctl.depth(s)))
            if ks <= 0:
                continue
            need = self.pool.pages_for(pos0 + ks + 1) \
                - self.pool.slot_pages(s)
            if need > 0 and not self.pool.grow_slot(s, need):
                # pool pressure: speculate only as deep as the pages
                # already held reach (k_s may hit 0 = plain decode row)
                ks = min(ks, self.pool.slot_pages(s)
                         * self.pool.page_size - pos0 - 1)
            if ks > 0:
                k_arr[s] = ks
                if not self._spec_verifying[s]:
                    self._spec_verifying[s] = True
                    self._emit("verify", rid, slot=s, k=ks)
        has_drafts = bool(k_arr.any())

        # ---- assemble + dispatch the verify/mixed tick ----
        base = ns * (1 + k)
        nt = base + npf * w
        pf_toks = np.zeros(npf * w, np.int32)
        tok_pos = np.zeros(nt, np.int32)
        tok_limit = np.zeros(nt, np.int32)
        tok_pos[:ns] = self._slot_len
        tok_limit[:ns] = cap
        dj = np.arange(k)[None, :]
        tok_pos[ns:base] = (self._slot_len[:, None] + 1 + dj) \
            .astype(np.int32).reshape(-1)
        tok_limit[ns:base] = np.where(dj < k_arr[:, None], cap, 0) \
            .astype(np.int32).reshape(-1)
        row_tab = np.zeros((ns + npf, nps), np.int32)
        row_tab[:ns] = self.pool.tables
        row_pos0 = np.zeros(ns + npf, np.int32)
        row_pos0[:ns] = self._slot_len
        row_len = np.ones(ns + npf, np.int32)
        row_len[:ns] += k_arr
        sample = np.zeros((ns, 1 + k), np.int32)
        sample[:, 0] = np.arange(ns)
        sample[:, 1:] = ns + np.arange(ns)[:, None] * k \
            + np.arange(k)[None, :]
        # per-row emission positions for the sampling law: a ticking
        # slot's primary token folds at slot_len + 1 (same as the
        # unified tick); a prefill finisher's at t0 (set below)
        sample_pos = (self._slot_len + 1).astype(np.int32)
        finishers = []
        for c, (s, rid, start, end, t0) in enumerate(chunks):
            coff = base + c * w
            req = self._requests[rid]
            pf_toks[c * w:c * w + (end - start)] = req.prompt[start:end]
            tok_pos[coff:coff + w] = start + np.arange(w)
            tok_limit[coff:coff + w] = t0
            row_tab[ns + c] = self.pool.tables[s]
            row_pos0[ns + c] = start
            row_len[ns + c] = end - start
            tok_pos[s] = end
            row_pos0[s] = end
            if end >= t0:
                finishers.append((s, rid))
                sample[s, 0] = coff + (t0 - 1 - start)
                sample_pos[s] = t0
        if sampling:
            tail = (last_tok, draft_flat, pf_toks, tok_pos, tok_limit,
                    row_tab, row_pos0, row_len, sample.reshape(-1),
                    k_arr,
                    np.ascontiguousarray(self._keys), sample_pos,
                    np.ascontiguousarray(self._temps),
                    np.ascontiguousarray(self._topks),
                    np.ascontiguousarray(self._topps), dprobs_m,
                    np.bool_(len(chunks) > 0), np.bool_(has_drafts))
        else:
            tail = (last_tok, draft_flat, pf_toks, tok_pos, tok_limit,
                    row_tab, row_pos0, row_len, sample.reshape(-1),
                    k_arr,
                    np.bool_(len(chunks) > 0), np.bool_(has_drafts))
        args = (self._stacked, self._other) + self._pool_args() + tail
        self._note_avals(self._tick_site, self._tick, args)
        with _quiet_donation():
            tok_m, acc = self._store_pools(self._tick(*args))

        # ---- overlap: chain draft tick N+1 on the un-materialized
        # verify outputs, BEFORE the host sync below — the sync then
        # hides under this dispatch's execution (ISSUE 20 tentpole) ----
        pend_new = None
        if sampling and self._spec_overlap and has_drafts:
            cm2 = np.zeros(ns, bool)
            ch_pos0 = np.zeros(ns, np.int32)
            for s in np.nonzero(k_arr)[0]:
                s = int(s)
                req = self._requests[self._slot_rid[s]]
                pos0 = int(self._slot_len[s])
                ks = int(k_arr[s])
                # the chained scan writes draft positions up to
                # pos0 + acc + k <= pos0 + ks + k; chain only when the
                # draft pages cover the worst case (best-effort — a
                # refusal just means a catch-up tick next step)
                if req.max_new - len(req.out) < 2 or \
                        not dr.grow_for(s, min(pos0 + ks + k + 1,
                                               cap)):
                    continue
                cm2[s] = True
                ch_pos0[s] = pos0
            if cm2.any():
                dtab2 = np.ascontiguousarray(dr.aux.tables)
                zi2 = np.zeros(ns, np.int32)
                dargs2 = (dr.stacked, dr.other, dr.kc, dr.vc, dtab2,
                          np.zeros((ns, w), np.int32), zi2, zi2, zi2,
                          np.full(ns, cap, np.int32),
                          np.ascontiguousarray(self._keys),
                          np.ascontiguousarray(self._temps),
                          np.ascontiguousarray(self._topks),
                          np.ascontiguousarray(self._topps),
                          tok_m, acc, ch_pos0, cm2,
                          np.bool_(False), np.bool_(True))
                self._note_avals(dr.site, dr.tick, dargs2)
                with _quiet_donation():
                    dr.kc, dr.vc, ch_drafts, ch_probs = \
                        dr.tick(*dargs2)
                pend_new = {"drafts": ch_drafts, "probs": ch_probs,
                            "valid": cm2, "pos0": ch_pos0}
                reg.counter("serving/spec_draft_ticks").add(1)
                reg.counter("serving/spec_chained_ticks").add(1)
        # the previous pend was consumed (or invalidated) above; the
        # new one must be installed before the absorb loop so _finish/
        # _spec_reset/_reclaim_draft invalidate the RIGHT entries
        self._spec_pend = pend_new

        # ---- chunk bookkeeping (same as the unified tick) ----
        for s, rid, start, end, t0 in chunks:
            self._slot_len[s] = end
            self._emit("chunk", rid, slot=s, start=start, end=end,
                       final=bool(end >= t0))
            if end >= t0:
                reg.counter("serving/prefills").add(1)
            self._insert_prefix(s, self._requests[rid].prompt, end)

        # ---- synchronous absorb: acceptance, rewind, finishes ----
        toks = np.asarray(tok_m)                       # [ns, 1+k]
        accs = np.asarray(acc)
        reg.counter("serving/token_syncs").add(1)
        now = time.perf_counter()
        eos = self.config.eos_token_id
        for s, rid in [(t, self._slot_rid[t]) for t in ticking] \
                + finishers:
            req = self._requests[rid]
            ks = int(k_arr[s])
            a = min(int(accs[s]), ks) if ks else 0
            pos0 = int(self._slot_len[s])
            emitted = 0
            finished = None
            for j in range(a + 1):
                tok = int(toks[s, j])
                req.out.append(tok)
                emitted += 1
                reg.counter("serving/tokens_generated").add(1)
                if req.first_token_t is None:
                    req.first_token_t = now
                    reg.histogram("serving/ttft_ms").observe(
                        (now - req.submit_t) * 1000.0)
                    self._emit("first_token", rid, slot=s)
                if eos is not None and tok == eos:
                    finished = "eos"
                    break
                if len(req.out) >= req.max_new:
                    finished = "max_new"
                    break
            if s in ticking_set:
                # the accepted prefix's KV is in the cache (written by
                # this verify row); the rejected tail is truncated off
                self._slot_len[s] = pos0 + emitted
                if ks:
                    gained = emitted - 1
                    reg.counter("serving/spec_drafted_tokens").add(ks)
                    reg.counter("serving/spec_accepted_tokens").add(
                        gained)
                    reg.histogram("serving/spec_accept_len").observe(
                        float(gained))
                    if self._spec_ctl is not None:
                        self._spec_ctl.observe(s, gained, ks)
                    self._emit("accept", rid, slot=s, accepted=gained,
                               drafted=ks)
                if s in gen_slots or s in chained:
                    # reconcile the chained tick against what actually
                    # absorbed: the chain's on-device seed assumed the
                    # full accepted prefix + correction was emitted and
                    # the slot kept ticking — anything else (EOS inside
                    # the window, max_new stop) invalidates it and the
                    # slot falls back to a catch-up tick
                    chain_ok = (pend_new is not None
                                and bool(pend_new["valid"][s])
                                and finished is None
                                and emitted == a + 1
                                and len(req.out) < req.max_new)
                    if chain_ok:
                        # the chained tick wrote the seed at the new
                        # frontier (and healed the full-accept hole):
                        # the draft cache is already caught up
                        dr.len[s] = pos0 + emitted
                    else:
                        if pend_new is not None:
                            pend_new["valid"][s] = False
                        # the draft's own speculation wrote the
                        # accepted tokens' KV — its frontier follows
                        # without repair; pages past it go back to the
                        # pool (the draft-side rewind)
                        dr.rewind(s, pos0 + min(emitted, k))
                if finished is None and ks:
                    # rewind: return pages past the new frontier (+1
                    # page headroom for the next tick's write) — the
                    # refcount machinery keeps shared pages alive
                    self.pool.shrink_slot(
                        s, self.pool.pages_for(
                            int(self._slot_len[s]) + 1))
            self._slot_dispatched[s] = len(req.out)
            if finished is not None:
                self._finish(s, rid, reason=finished)
        reg.counter("serving/ticks").add(1)
        if chunks:
            reg.counter("serving/prefill_chunks").add(len(chunks))
        reg.gauge("serving/decode_batch").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows").set(
            float(len(ticking) + len(chunks)))
        reg.gauge("serving/mixed_rows_decode").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows_prefill").set(float(len(chunks)))
        reg.gauge("serving/spec_rows").set(float(int((k_arr > 0).sum())))
        # mean OFFERED draft depth across speculating slots this tick
        # (0.0 when nobody speculated): under adaptive k this is the
        # live evidence of convergence — full depth at high accept,
        # decaying toward 0 as drafts keep getting rejected
        reg.gauge("serving/spec_k_effective").set(
            float(k_arr[k_arr > 0].mean()) if (k_arr > 0).any()
            else 0.0)
        drafted = reg.counter("serving/spec_drafted_tokens").value
        if drafted:
            reg.gauge("serving/spec_accept_rate").set(
                reg.counter("serving/spec_accepted_tokens").value
                / drafted)
        # the draft cache's footprint in the SHARED pool (ISSUE 20):
        # pages held by draft tables / pages allocated overall — the
        # residency ledger prices draft and target bytes together
        dp = dr.aux.total_pages()
        reg.gauge("serving/draft_pool_pages").set(float(dp))
        share = dp / max(self.pool.allocator.num_allocated, 1)
        reg.gauge("serving/draft_pool_share").set(share)
        # peak survives the end-of-run release (slots return their
        # draft pages on finish, so the plain gauge reads 0 by the
        # time a bench harness snapshots the registry)
        reg.gauge("serving/draft_pool_share_peak").set_max(share)
        return True

    # ------------------------------------------------------------------
    # legacy two-dispatch mode (attention_kernel="legacy"): the
    # pre-unification engine — a dedicated decode tick plus a separate
    # suffix-prefill program alternating on the hot path. Kept ONLY so
    # serve_bench.py can measure what the dispatch collapse buys;
    # outputs are bitwise-equal to the unified tick (same shared
    # attention spelling underneath).
    # ------------------------------------------------------------------
    def _prefill_chunks(self) -> bool:
        """Advance prefilling slots by up to ``prefill_chunks_per_tick``
        immediately-dispatched chunks, oldest admission first."""
        any_dispatch = False
        for _ in range(self.config.prefill_chunks_per_tick):
            s = self._next_prefill_slot({})
            if s is None:
                break
            chunk = self._open_chunk(s, {})
            if chunk is None:
                break
            self._dispatch_prefill_chunk(*chunk)
            any_dispatch = True
        return any_dispatch

    def _dispatch_prefill_chunk(self, s: int, rid: int, start: int,
                                end: int, t0: int) -> None:
        req = self._requests[rid]
        chunk = self.prefill_chunk
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :end - start] = req.prompt[start:end]
        page_row = np.ascontiguousarray(self.pool.tables[s])
        args = (self._stacked, self._other, self.pool.k, self.pool.v,
                toks, np.int32(start), np.int32(t0), page_row, req.key,
                self._temps[s:s + 1], self._topks[s:s + 1],
                self._topps[s:s + 1])
        self._note_avals(self._prefill_site, self._prefill, args)
        with _quiet_donation():
            self.pool.k, self.pool.v, tok0 = self._prefill(*args)
        _registry().counter("serving/prefill_chunks").add(1)
        self._emit("chunk", rid, slot=s, start=start, end=end,
                   final=bool(end >= t0))
        if end >= t0:                # final chunk: tok0 is real
            self._last_tok = self._last_tok.at[s].set(tok0[0])
            self._inflight.append(_Inflight(tok0, [(0, s, req.rid)]))
            self.max_inflight_seen = max(self.max_inflight_seen,
                                         len(self._inflight))
            self._slot_dispatched[s] = 1
            self._slot_len[s] = t0
            _registry().counter("serving/prefills").add(1)
        else:
            self._slot_len[s] = end
        # publish the pages this chunk completed (progressively: a long
        # shared prompt becomes hittable page-by-page, mid-prefill)
        self._insert_prefix(s, req.prompt, int(self._slot_len[s]))

    def _dispatch_legacy_tick(self) -> bool:
        ticking = self._ticking_slots()
        if not ticking:
            return False
        tab = np.ascontiguousarray(self.pool.tables)
        pos = np.ascontiguousarray(self._slot_len)
        keys = np.ascontiguousarray(self._keys)
        args = (self._stacked, self._other, self.pool.k, self.pool.v,
                tab, pos, self._last_tok, keys,
                np.ascontiguousarray(self._temps),
                np.ascontiguousarray(self._topks),
                np.ascontiguousarray(self._topps))
        self._note_avals(self._tick_site, self._tick, args)
        with _quiet_donation():
            self.pool.k, self.pool.v, tok = self._tick(*args)
        self._last_tok = tok
        meta = [(s, s, self._slot_rid[s]) for s in ticking]
        self._inflight.append(_Inflight(tok, meta))
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     len(self._inflight))
        for s in ticking:
            self._slot_len[s] += 1
            self._slot_dispatched[s] += 1
        _registry().counter("serving/ticks").add(1)
        reg = _registry()
        reg.gauge("serving/decode_batch").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows_decode").set(float(len(ticking)))
        reg.gauge("serving/mixed_rows_prefill").set(0.0)
        return True

    # ------------------------------------------------------------------
    # compiled program bodies
    # ------------------------------------------------------------------
    def _sample_tok(self, logits, keys, positions, temps, top_ks, top_ps):
        """Token choice from last-token logits [N, V]. Greedy mirrors
        ops/decoding.greedy_decode (argmax of f32 log_softmax — parity);
        sampling applies the PER-ROW temperature/top-k/top-p arrays and
        folds each slot's key by the ABSOLUTE position of the emitted
        token, so a request's stream is independent of scheduling,
        preemption, and its neighbours' sampling params."""
        if self.config.decode == "greedy":
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return jnp.argmax(lp, axis=-1).astype(jnp.int32)
        from ..ops.decoding import apply_top_k_top_p_per_row

        lg = logits.astype(jnp.float32) / \
            jnp.maximum(temps, 1e-6)[:, None]
        lg = apply_top_k_top_p_per_row(lg, top_ks, top_ps)
        lp = jax.nn.log_softmax(lg, axis=-1)

        def one(key, pos, row):
            return jax.random.categorical(jax.random.fold_in(key, pos), row)

        return jax.vmap(one)(keys, positions, lp).astype(jnp.int32)

    def _make_legacy_tick(self):
        mcfg = self.model_config
        ps = self.pool.page_size
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        eps = mcfg.layer_norm_eps
        nslots = self.config.num_slots
        impl = self._impl
        site = self._tick_site

        from ..models.gpt import _ln, gpt_block_body
        from ..ops.paged_attention import paged_decode_attention

        nps = self.pool.pages_per_slot
        cap = nps * ps

        def tick(stacked, other, kpool, vpool, tab, pos, tok, keys,
                 temps, top_ks, top_ps):
            _recompile.mark_trace(site, kpool, tab, pos, tok)
            wte = other["embeddings.wte.weight"]
            wpe = other["embeddings.wpe.weight"]
            x = wte[tok[:, None]] + wpe[pos[:, None]]        # [B, 1, h]
            # a slot that finished at EXACT capacity keeps riding the
            # fixed-shape tick until its tokens drain, with pos == cap;
            # clamping that gather would silently stomp the slot's LAST
            # page (absolute position cap - page_size) — which _finish
            # is about to publish into the prefix index. Route every
            # out-of-range write to the null page instead, like the
            # prefill pad path.
            page = jnp.where(
                pos < cap,
                tab[jnp.arange(nslots), jnp.minimum(pos // ps, nps - 1)],
                0)
            off = pos % ps

            def block(xc, inp):
                p, kpl0, vpl0 = inp

                def attend(q, kk, vv):
                    kpl = kpl0.at[page, off].set(kk[:, 0])
                    vpl = vpl0.at[page, off].set(vv[:, 0])
                    o = paged_decode_attention(q, kpl, vpl, tab, pos,
                                               impl=impl)
                    return o, (kpl, vpl)

                return gpt_block_body(xc, p, eps, nh, hd, attend)

            x, (kpool, vpool) = jax.lax.scan(
                block, x, (stacked, kpool, vpool))
            x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
            last = x[:, -1]
            if "lm_head.weight" in other:
                logits = last @ other["lm_head.weight"]
            else:
                logits = last @ wte.T
            nxt = self._sample_tok(logits, keys, pos + 1, temps,
                                   top_ks, top_ps)
            return kpool, vpool, nxt

        return tick

    def _make_prefill_chunk(self):
        """Legacy mode's second compiled program: one fixed-shape
        suffix-prefill over ``gpt_paged_suffix_apply`` (itself now a
        delegation into the unified ragged forward). The chunk start /
        true prompt length ride as traced scalars, so every chunk of
        every prompt shares this one compiled program. The sampled
        token is only meaningful on the final chunk (the host ignores
        it otherwise)."""
        mcfg = self.model_config
        site = self._prefill_site
        chunk = self.prefill_chunk

        from ..models.gpt import gpt_paged_suffix_apply

        def prefill(stacked, other, kpool, vpool, tokens, pos0, true_len,
                    page_row, key, temp, top_k, top_p):
            _recompile.mark_trace(site, tokens, kpool, pos0)
            li = jnp.clip(true_len - 1 - pos0, 0, chunk - 1)
            logits, kpool, vpool = gpt_paged_suffix_apply(
                mcfg, stacked, other, kpool, vpool, tokens, pos0,
                true_len, page_row, li)
            tok0 = self._sample_tok(logits, key[None], true_len[None],
                                    temp, top_k, top_p)
            return kpool, vpool, tok0

        return prefill
