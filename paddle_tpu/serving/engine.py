"""Continuous-batching decode engine over the paged KV cache.

The dense ``GPT.generate`` path is one jitted prefill+scan program per
request batch: every admitted prompt pays ``S_max`` of cache HBM,
nobody can join or leave mid-decode, and mixed prompt lengths force
padding waste or a retrace. This engine restructures serving the way
the roadmap's cross-replica-sharding paper restructures the weight
update — so the hardware never idles on work another request could
fill:

- **Fixed-shape decode tick.** One jitted program over ``num_slots``
  cache slots advances every resident request by one token per call.
  The program shape never depends on which slots are live, so it
  traces exactly once (asserted via ``profiler.recompile`` telemetry).
  Per-request sampling params (temperature / top-k / top-p) ride the
  tick as ``[num_slots]`` arrays — vectorized inside the compiled
  program, no retrace per parameter combination.
- **Chunked prefill** (Sarathi-style). A prompt is prefilled in
  fixed-size chunks, at most ``prefill_chunks_per_tick`` per scheduler
  step, each attending over (aliased prefix pages + earlier chunks +
  itself) via the suffix path ``models/gpt.gpt_paged_suffix_apply``.
  A long prompt therefore never blocks resident decode slots for more
  than one chunk's compute, and prefill compiles ONE chunk shape
  (retraces collapse to a single ``serving.prefill`` trace) instead of
  one program per length bucket.
- **Prefix caching.** Fully-written prompt pages are registered in a
  hash-trie index (``paged_cache.PrefixCache``) keyed on page-aligned
  token chunks. Admission looks up the longest cached prefix, aliases
  those pages into the slot's table (refcounted — a page frees only
  when its last holder lets go), and prefills only the suffix; a
  prompt diverging from a cached chunk mid-page copy-on-writes that
  one tail page. Unreferenced cached pages are evicted LRU under pool
  pressure. Preemption inserts the victim's own fully-written pages
  before releasing the slot, so the requeued request re-aliases its
  own work instead of re-prefilling it.
- **Deferred host sync** (the PR-3 async-pipeline idiom): each tick's
  token vector stays an unmaterialized device array; the host
  dispatches tick N+1 (and prefill chunks, via donated pool buffers)
  before materializing tick N, keeping up to ``max_inflight`` ticks in
  flight. Scheduling that must be host-deterministic (positions, page
  growth, max-token stops) never reads device data; only EOS discovery
  rides the lagged window.
- **Exhaustion → eviction → preemption.** If the pool cannot grow a
  slot, the engine evicts unreferenced cached pages, drains, retries,
  then preempts the youngest request: its generated prefix is requeued
  as a longer prompt (and its pages stay cached, so re-prefill is a
  prefix hit). Sampling keys are folded per absolute position, so a
  preempted request's tokens do not depend on scheduling.

Greedy paged decode is **bitwise identical** to the dense
``generate()`` on the same weights whenever the slot capacity
``pages_per_slot * page_size`` equals the dense path's
``prompt + max_new_tokens`` (the attention reduction length must match
exactly — zero-tail padding is not bitwise-neutral). Prefix caching
preserves this bitwise: aliased pages hold KV that is identical by
construction (same tokens, same positions, same reduction lengths), so
the cached engine, the uncached engine and the dense path all agree —
tests/test_serving.py pins cached-vs-uncached across admission orders.

Profiler signals: ``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util``, ``serving/ttft_ms`` (histogram),
``serving/prefill_queue_wait_ms`` (histogram: submit → first prefill
chunk), ``serving/tokens_per_sec``, ``serving/tokens_generated``,
``serving/prefills``, ``serving/prefill_chunks``, ``serving/ticks``,
``serving/preemptions``, ``serving/requests_finished``,
``serving/token_syncs``, ``serving/prefix_lookups``,
``serving/prefix_hit_tokens``; refcount traffic under ``cache_share/*``
(shares, releases, cow_copies, prefix_evictions).
"""
from __future__ import annotations

import time
import warnings
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..profiler import recompile as _recompile
from ..profiler import registry as _registry
from .paged_cache import PagePool

__all__ = ["ServingConfig", "ServingEngine", "Request"]


@contextmanager
def _quiet_donation():
    """CPU jax may decline buffer donation for the page pools; the
    fallback copy is correct, just slower — don't spam the log for it.
    Scoped to the engine's own dispatches: a global filter would also
    swallow the training stack's donation-failure warnings (a real perf
    signal in hybrid.py's jitted step)."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    """Engine knobs. Pool sizing math: the pool holds
    ``num_pages - 1`` allocatable pages (page 0 is the null page) of
    ``page_size`` tokens each, shared by ``num_slots`` resident
    requests of at most ``pages_per_slot`` pages
    (``slot_capacity = pages_per_slot * page_size`` tokens). Sizing
    ``num_pages - 1 < num_slots * pages_per_slot`` oversubscribes the
    pool — legal, served by prefix-cache eviction then preemption when
    it binds. With ``prefix_cache`` on, shared prompt pages are charged
    ONCE regardless of how many slots alias them, so effective
    capacity grows with prompt overlap."""

    num_slots: int = 8
    page_size: int = 16
    pages_per_slot: int = 0          # default: ceil(max_seq_len / page_size)
    num_pages: int = 0               # default: full residency + null page
    prefill_chunk: int = 0           # tokens per prefill chunk (0: 2 pages)
    prefill_chunks_per_tick: int = 1  # prefill work budget per step
    prefix_cache: bool = True        # share prompt-prefix pages
    max_inflight: int = 2            # unmaterialized decode ticks in flight
    decode: str = "greedy"           # 'greedy' | 'sampling'
    temperature: float = 1.0         # sampling defaults; per-request
    top_k: int = 0                   #   overrides ride submit()
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    attention_impl: str = "xla"      # 'xla' | 'pallas' (ops/paged_attention)


@dataclass
class Request:
    rid: int
    prompt: np.ndarray               # current prompt (grows on preemption)
    max_new: int                     # tokens still wanted (shrinks on preempt)
    key: np.ndarray                  # uint32[2] sampling key (absolute-pos folds)
    out: List[int] = field(default_factory=list)
    done: bool = False
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    orig_prompt_len: int = 0         # for result accounting across preemption
    temperature: Optional[float] = None   # per-request sampling overrides
    top_k: Optional[int] = None           #   (None -> engine config default)
    top_p: Optional[float] = None


class _Inflight:
    __slots__ = ("tok", "meta")

    def __init__(self, tok, meta):
        self.tok = tok               # device int32 array
        self.meta = meta             # [(index_into_tok, slot, rid)]


def _copy_pages(kpool, vpool, src, dst):
    """Copy-on-write: duplicate page ``src`` into ``dst`` across all
    layers (one compiled program, pools donated)."""
    return (kpool.at[:, dst].set(kpool[:, src]),
            vpool.at[:, dst].set(vpool[:, src]))


class ServingEngine:
    """Continuous-batching serving runtime for a dense ``GPT`` model.

    ::

        eng = ServingEngine(model, ServingConfig(num_slots=8))
        rid = eng.submit(prompt_ids, max_new_tokens=32)
        out = eng.run()[rid]          # np.int32 generated ids
    """

    def __init__(self, model, config: Optional[ServingConfig] = None):
        cfg = config or ServingConfig()
        mcfg = model.config
        if cfg.decode not in ("greedy", "sampling"):
            raise ValueError(f"unknown decode mode {cfg.decode!r}")
        if cfg.prefill_chunks_per_tick < 1:
            raise ValueError("prefill_chunks_per_tick must be >= 1")
        self.config = cfg
        self.model_config = mcfg
        self._stacked, self._other = model._decode_state()
        self._dtype = self._other["embeddings.wte.weight"].dtype
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        ps = cfg.page_size
        pages_per_slot = cfg.pages_per_slot or -(-mcfg.max_seq_len // ps)
        num_pages = cfg.num_pages or cfg.num_slots * pages_per_slot + 1
        self.pool = PagePool(mcfg.num_layers, num_pages, ps, nh, hd,
                             cfg.num_slots, pages_per_slot,
                             dtype=self._dtype,
                             prefix_cache=cfg.prefix_cache)
        self.prefill_chunk = int(cfg.prefill_chunk) or 2 * ps
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        b_slots = cfg.num_slots
        # host scheduling state (never reads device data)
        self._slot_rid: List[Optional[int]] = [None] * b_slots
        self._slot_len = np.zeros(b_slots, np.int32)      # tokens in cache
        self._slot_prompt = np.zeros(b_slots, np.int32)   # current prompt len
        self._slot_dispatched = np.zeros(b_slots, np.int64)  # tokens emitted
        self._slot_admit_seq = np.zeros(b_slots, np.int64)
        self._slot_looked_up = [False] * b_slots
        self._admit_seq = 0
        self._queue: deque[Request] = deque()
        self._requests: Dict[int, Request] = {}
        self._next_rid = 0
        self._inflight: deque[_Inflight] = deque()
        self.max_inflight_seen = 0
        # device state
        self._last_tok = jnp.zeros((b_slots,), jnp.int32)
        self._keys = np.zeros((b_slots, 2), np.uint32)
        # per-slot sampling params (fixed-shape tick arguments)
        self._temps = np.full(b_slots, cfg.temperature, np.float32)
        self._topks = np.full(b_slots, cfg.top_k, np.int32)
        self._topps = np.full(b_slots, cfg.top_p, np.float32)
        self._base_key = np.asarray(jax.random.PRNGKey(cfg.seed))
        # compiled programs: ONE tick site (asserted single-trace) and ONE
        # prefill-chunk site — chunked prefill has a single shape, so it
        # also traces exactly once (the per-bucket retraces are gone)
        self._tick_site = _recompile.unique_site("serving.tick")
        self._prefill_site = _recompile.unique_site("serving.prefill")
        self._tick = jax.jit(self._make_tick(), donate_argnums=(2, 3))
        self._prefill = jax.jit(self._make_prefill_chunk(),
                                donate_argnums=(2, 3))
        self._copy = jax.jit(_copy_pages, donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               key: Optional[np.ndarray] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> int:
        """Queue one request. ``temperature``/``top_k``/``top_p``
        override the engine-global sampling params for this request
        only (ignored under greedy decode). Returns its request id."""
        p = np.asarray(prompt_ids, np.int32).reshape(-1)
        t0 = p.shape[0]
        cap = self.pool.slot_capacity
        if t0 < 1 or max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        if t0 + max_new_tokens - 1 > cap:
            raise ValueError(
                f"prompt {t0} + {max_new_tokens} new tokens needs "
                f"{t0 + max_new_tokens - 1} cache positions; slot capacity "
                f"is {cap} (pages_per_slot * page_size) — raise "
                "pages_per_slot or page_size")
        if self.pool.pages_for(t0 + max_new_tokens - 1) > \
                self.pool.allocator.num_pages - 1:
            raise ValueError("request exceeds the whole page pool")
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = np.asarray(jax.random.fold_in(self._base_key, rid))
        req = Request(rid=rid, prompt=p, max_new=int(max_new_tokens),
                      key=np.asarray(key, np.uint32),
                      submit_t=time.perf_counter(), orig_prompt_len=t0,
                      temperature=temperature, top_k=top_k, top_p=top_p)
        self._requests[rid] = req
        self._queue.append(req)
        return rid

    def step(self) -> bool:
        """One scheduler iteration: bound the in-flight window, admit
        into free slots, advance prefill by up to
        ``prefill_chunks_per_tick`` chunks, grow pages (preempting on
        exhaustion), dispatch one decode tick. Returns whether any
        device work was dispatched."""
        self._drain(self.config.max_inflight)
        self._admit()
        dispatched = self._prefill_chunks()
        self._grow_pages()
        dispatched = self._dispatch_tick() or dispatched
        reg = _registry()
        reg.gauge("serving/queue_depth").set(float(len(self._queue)))
        reg.gauge("serving/active_slots").set(
            float(sum(r is not None for r in self._slot_rid)))
        reg.gauge("serving/page_util").set(self.pool.allocator.utilization())
        return dispatched

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until every submitted request finished; returns
        {rid: generated ids np.int32[<=max_new]}."""
        t0 = time.perf_counter()
        tokens0 = self._tokens_done()
        while True:
            progressed = self.step()
            if not progressed:
                if self._inflight:
                    self._drain(0)
                    continue
                if all(r is None for r in self._slot_rid):
                    if not self._queue:
                        break
                    # every slot free, window empty, still can't admit
                    raise RuntimeError(
                        "serving queue stalled: page pool too small for "
                        "the queued prompt")
                raise RuntimeError(
                    "serving scheduler deadlock: resident requests but "
                    "nothing dispatchable")
        wall = max(time.perf_counter() - t0, 1e-9)
        done = self._tokens_done() - tokens0
        _registry().gauge("serving/tokens_per_sec").set(done / wall)
        return {rid: np.asarray(r.out, np.int32)
                for rid, r in self._requests.items() if r.done}

    def drain(self, target: int = 0) -> None:
        """Materialize in-flight ticks until at most ``target`` remain."""
        self._drain(target)

    def idle(self) -> bool:
        """True when nothing is queued, resident, or in flight."""
        return (not self._queue and not self._inflight
                and all(r is None for r in self._slot_rid))

    def reset_results(self) -> None:
        """Forget finished requests (long-running host keeps memory flat)."""
        self._requests = {rid: r for rid, r in self._requests.items()
                          if not r.done}

    def _tokens_done(self) -> int:
        return sum(len(r.out) for r in self._requests.values())

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _drain(self, target: int) -> None:
        """Materialize in-flight ticks oldest-first until at most
        ``target`` remain. The ONLY place device data reaches the host."""
        while len(self._inflight) > target:
            ent = self._inflight.popleft()
            toks = np.asarray(ent.tok)
            _registry().counter("serving/token_syncs").add(1)
            now = time.perf_counter()
            for idx, slot, rid in ent.meta:
                req = self._requests[rid]
                if req.done:
                    continue        # EOS discovered while this was in flight
                tok = int(toks[idx])
                req.out.append(tok)
                _registry().counter("serving/tokens_generated").add(1)
                if req.first_token_t is None:
                    req.first_token_t = now
                    _registry().histogram("serving/ttft_ms").observe(
                        (now - req.submit_t) * 1000.0)
                eos = self.config.eos_token_id
                # max_new counts tokens wanted since the LAST (re)queue —
                # preemption moved earlier output into the prompt and
                # shrank max_new to the remainder
                if (eos is not None and tok == eos) or \
                        len(req.out) >= req.max_new:
                    self._finish(slot, rid)

    def _insert_prefix(self, slot: int, tokens: np.ndarray,
                       written: int) -> None:
        """Register ``slot``'s fully-written pages (KV for
        ``tokens[:written]``) in the prefix index."""
        if self.pool.prefix is None:
            return
        n_full = min(written, tokens.shape[0]) // self.pool.page_size
        if n_full:
            self.pool.prefix.insert(
                tokens[:n_full * self.pool.page_size],
                [int(p) for p in self.pool.tables[slot, :n_full]])

    def _finish(self, slot: int, rid: int) -> None:
        req = self._requests[rid]
        req.done = True
        if self._slot_rid[slot] == rid:
            # cache the finished sequence's pages (prompt AND generated
            # full pages) before release: an identical follow-up
            # conversation prefix becomes a prefix hit
            seq = np.concatenate(
                [req.prompt, np.asarray(req.out, np.int32)])
            self._insert_prefix(slot, seq, int(self._slot_len[slot]))
            self.pool.release_slot(slot)
            self._slot_rid[slot] = None
            self._slot_len[slot] = 0
        # fold the preemption-era prefix back into the result
        extra = req.prompt[req.orig_prompt_len:]
        if extra.size:
            req.out = list(extra) + req.out
        _registry().counter("serving/requests_finished").add(1)

    def _admit(self) -> None:
        """Move queued requests into free slots. Page allocation is
        deferred to the per-chunk prefill path (so the prefix lookup
        runs as late as possible — an identical prompt admitted a few
        ticks later sees the first tenant's pages already cached)."""
        free = [s for s, r in enumerate(self._slot_rid) if r is None]
        while self._queue and free:
            req = self._queue.popleft()
            slot = free.pop()
            self._slot_rid[slot] = req.rid
            self._slot_len[slot] = 0
            self._slot_prompt[slot] = req.prompt.shape[0]
            self._slot_dispatched[slot] = 0
            self._slot_looked_up[slot] = False
            self._admit_seq += 1
            self._slot_admit_seq[slot] = self._admit_seq
            self._keys[slot] = req.key
            c = self.config
            self._temps[slot] = (c.temperature if req.temperature is None
                                 else req.temperature)
            self._topks[slot] = c.top_k if req.top_k is None else req.top_k
            self._topps[slot] = c.top_p if req.top_p is None else req.top_p

    # ------------------------------------------------------------------
    # chunked prefill + prefix cache
    # ------------------------------------------------------------------
    def _prefill_chunks(self) -> bool:
        """Advance prefilling slots by up to ``prefill_chunks_per_tick``
        chunks, oldest admission first (completing one request's
        prefill start-to-finish both minimizes its TTFT and publishes
        its pages before the next identical prompt looks them up)."""
        any_dispatch = False
        for _ in range(self.config.prefill_chunks_per_tick):
            pending = [s for s, rid in enumerate(self._slot_rid)
                       if rid is not None
                       and self._slot_len[s] < self._slot_prompt[s]]
            if not pending:
                break
            s = min(pending, key=lambda x: self._slot_admit_seq[x])
            if not self._advance_prefill(s):
                break
            any_dispatch = True
        return any_dispatch

    def _lookup_prefix(self, slot: int, req: Request) -> None:
        """Alias the longest cached page-aligned prefix of the prompt
        into ``slot`` (plus one copy-on-write page when the prompt
        diverges from a cached chunk mid-page) and start prefill at the
        first uncached position."""
        if self.pool.prefix is None:
            return
        full_pages, partial = self.pool.prefix.lookup(req.prompt)
        _registry().counter("serving/prefix_lookups").add(1)
        hit = 0
        if full_pages:
            self.pool.share_into_slot(slot, full_pages)
            hit = len(full_pages) * self.pool.page_size
        if partial is not None:
            src, lcp = partial
            # pin the donor page: the grow below may evict unreferenced
            # cached pages — src must not be reclaimed (or handed back
            # as the destination) mid-copy
            self.pool.allocator.share([src])
            try:
                if self.pool.grow_slot(slot, 1):
                    dst = self.pool.tables[slot,
                                           self.pool.slot_pages(slot) - 1]
                    with _quiet_donation():
                        self.pool.k, self.pool.v = self._copy(
                            self.pool.k, self.pool.v,
                            np.int32(src), np.int32(dst))
                    hit += lcp
                    _registry().counter("cache_share/cow_copies").add(1)
            finally:
                self.pool.allocator.free([src])
        self._slot_len[slot] = hit
        if hit:
            _registry().counter("serving/prefix_hit_tokens").add(hit)

    def _advance_prefill(self, s: int) -> bool:
        """Dispatch one prefill chunk for slot ``s`` (running the prefix
        lookup first if this is the slot's first chunk). Returns whether
        a chunk was dispatched; raises when the pool cannot cover the
        chunk even after draining, prefix eviction and preemption."""
        req = self._requests[self._slot_rid[s]]
        if not self._slot_looked_up[s]:
            self._slot_looked_up[s] = True
            _registry().histogram("serving/prefill_queue_wait_ms").observe(
                (time.perf_counter() - req.submit_t) * 1000.0)
            self._lookup_prefix(s, req)
        t0 = int(self._slot_prompt[s])
        start = int(self._slot_len[s])
        end = min(start + self.prefill_chunk, t0)
        need = self.pool.pages_for(end) - self.pool.slot_pages(s)
        if not self._acquire_pages(s, need):
            return False             # finished in the drain / requeued
        self._dispatch_prefill_chunk(s, req, start, end, t0)
        return True

    def _acquire_pages(self, s: int, need: int) -> bool:
        """Grow slot ``s`` by ``need`` pages, escalating: free list
        (+ prefix-cache LRU eviction inside ``grow_slot``) -> drain
        in-flight finishes -> preempt youngest-first. The ONE
        exhaustion-recovery path, shared by prefill chunks and decode
        growth. Returns False when ``s`` itself was freed along the way
        (finished in the drain, or became its own preemption victim);
        raises only in the can't-happen state where the pool cannot
        cover a request ``submit()`` already validated against it."""
        if need <= 0 or self.pool.grow_slot(s, need):
            return True
        self._drain(0)
        if self._slot_rid[s] is None:
            return False
        if self.pool.grow_slot(s, need):
            return True
        if not any(x != s and self._slot_rid[x] is not None
                   for x in range(self.config.num_slots)):
            raise RuntimeError(
                "serving pool exhausted: cannot cover a resident "
                "request even with the prefix cache drained and no "
                "co-resident to preempt")
        self._preempt_for(s, need)
        return self._slot_rid[s] is not None

    def _dispatch_prefill_chunk(self, s: int, req: Request, start: int,
                                end: int, t0: int) -> None:
        chunk = self.prefill_chunk
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :end - start] = req.prompt[start:end]
        page_row = np.ascontiguousarray(self.pool.tables[s])
        with _quiet_donation():
            self.pool.k, self.pool.v, tok0 = self._prefill(
                self._stacked, self._other, self.pool.k, self.pool.v,
                toks, np.int32(start), np.int32(t0), page_row, req.key,
                self._temps[s:s + 1], self._topks[s:s + 1],
                self._topps[s:s + 1])
        _registry().counter("serving/prefill_chunks").add(1)
        if end >= t0:                # final chunk: tok0 is real
            self._last_tok = self._last_tok.at[s].set(tok0[0])
            self._inflight.append(_Inflight(tok0, [(0, s, req.rid)]))
            self.max_inflight_seen = max(self.max_inflight_seen,
                                         len(self._inflight))
            self._slot_dispatched[s] = 1
            self._slot_len[s] = t0
            _registry().counter("serving/prefills").add(1)
        else:
            self._slot_len[s] = end
        # publish the pages this chunk completed (progressively: a long
        # shared prompt becomes hittable page-by-page, mid-prefill)
        self._insert_prefix(s, req.prompt, int(self._slot_len[s]))

    # ------------------------------------------------------------------
    # decode scheduling
    # ------------------------------------------------------------------
    def _ticking_slots(self) -> List[int]:
        """Slots that should advance this tick: resident, prefill
        complete, not finished, and with emissions still owed. A slot
        whose final token is already dispatched stops ticking
        immediately (max-token stop is host-deterministic); EOS stops
        lag by <= max_inflight ticks."""
        out = []
        for s, rid in enumerate(self._slot_rid):
            if rid is None:
                continue
            req = self._requests[rid]
            if not req.done and \
                    1 <= self._slot_dispatched[s] < req.max_new:
                out.append(s)
        return out

    def _grow_pages(self) -> None:
        for s in self._ticking_slots():
            if self._slot_rid[s] is None:
                continue            # freed by an earlier drain/preempt
            need_page = int(self._slot_len[s]) // self.pool.page_size
            if need_page < self.pool.slot_pages(s):
                continue
            self._acquire_pages(s, 1)

    def _preempt_for(self, needy_slot: int, need: int) -> None:
        """Free ``need`` pages by requeueing the youngest resident
        request (its generated prefix becomes prompt, so no work is
        redone twice — and its fully-written pages go into the prefix
        index first, so the re-prefill is a prefix hit)."""
        live = [s for s in range(self.config.num_slots)
                if self._slot_rid[s] is not None]
        victim = max(live, key=lambda s: self._slot_admit_seq[s])
        rid = self._slot_rid[victim]
        req = self._requests[rid]
        # window was drained before preemption, so req.out is current
        req.prompt = np.concatenate(
            [req.prompt, np.asarray(req.out, np.int32)])
        req.max_new -= len(req.out)
        req.out = []
        self._insert_prefix(victim, req.prompt, int(self._slot_len[victim]))
        self._queue.appendleft(req)
        self.pool.release_slot(victim)
        self._slot_rid[victim] = None
        self._slot_len[victim] = 0
        _registry().counter("serving/preemptions").add(1)
        if victim != needy_slot and self._slot_rid[needy_slot] is not None:
            if not self.pool.grow_slot(needy_slot, need):
                self._preempt_for(needy_slot, need)

    def _dispatch_tick(self) -> bool:
        ticking = self._ticking_slots()
        if not ticking:
            return False
        tab = np.ascontiguousarray(self.pool.tables)
        pos = np.ascontiguousarray(self._slot_len)
        keys = np.ascontiguousarray(self._keys)
        with _quiet_donation():
            self.pool.k, self.pool.v, tok = self._tick(
                self._stacked, self._other, self.pool.k, self.pool.v,
                tab, pos, self._last_tok, keys,
                np.ascontiguousarray(self._temps),
                np.ascontiguousarray(self._topks),
                np.ascontiguousarray(self._topps))
        self._last_tok = tok
        meta = [(s, s, self._slot_rid[s]) for s in ticking]
        self._inflight.append(_Inflight(tok, meta))
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     len(self._inflight))
        for s in ticking:
            self._slot_len[s] += 1
            self._slot_dispatched[s] += 1
        _registry().counter("serving/ticks").add(1)
        _registry().gauge("serving/decode_batch").set(float(len(ticking)))
        return True

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _sample_tok(self, logits, keys, positions, temps, top_ks, top_ps):
        """Token choice from last-token logits [N, V]. Greedy mirrors
        ops/decoding.greedy_decode (argmax of f32 log_softmax — parity);
        sampling applies the PER-ROW temperature/top-k/top-p arrays and
        folds each slot's key by the ABSOLUTE position of the emitted
        token, so a request's stream is independent of scheduling,
        preemption, and its neighbours' sampling params."""
        if self.config.decode == "greedy":
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return jnp.argmax(lp, axis=-1).astype(jnp.int32)
        from ..ops.decoding import apply_top_k_top_p_per_row

        lg = logits.astype(jnp.float32) / \
            jnp.maximum(temps, 1e-6)[:, None]
        lg = apply_top_k_top_p_per_row(lg, top_ks, top_ps)
        lp = jax.nn.log_softmax(lg, axis=-1)

        def one(key, pos, row):
            return jax.random.categorical(jax.random.fold_in(key, pos), row)

        return jax.vmap(one)(keys, positions, lp).astype(jnp.int32)

    def _make_tick(self):
        mcfg = self.model_config
        ps = self.pool.page_size
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        eps = mcfg.layer_norm_eps
        nslots = self.config.num_slots
        impl = self.config.attention_impl
        site = self._tick_site

        from ..models.gpt import _ln, gpt_block_body
        from ..ops.paged_attention import paged_decode_attention

        nps = self.pool.pages_per_slot
        cap = nps * ps

        def tick(stacked, other, kpool, vpool, tab, pos, tok, keys,
                 temps, top_ks, top_ps):
            _recompile.mark_trace(site, kpool, tab, pos, tok)
            wte = other["embeddings.wte.weight"]
            wpe = other["embeddings.wpe.weight"]
            x = wte[tok[:, None]] + wpe[pos[:, None]]        # [B, 1, h]
            # a slot that finished at EXACT capacity keeps riding the
            # fixed-shape tick until its tokens drain, with pos == cap;
            # clamping that gather would silently stomp the slot's LAST
            # page (absolute position cap - page_size) — which _finish
            # is about to publish into the prefix index. Route every
            # out-of-range write to the null page instead, like the
            # prefill pad path.
            page = jnp.where(
                pos < cap,
                tab[jnp.arange(nslots), jnp.minimum(pos // ps, nps - 1)],
                0)
            off = pos % ps

            def block(xc, inp):
                p, kpl0, vpl0 = inp

                def attend(q, kk, vv):
                    kpl = kpl0.at[page, off].set(kk[:, 0])
                    vpl = vpl0.at[page, off].set(vv[:, 0])
                    o = paged_decode_attention(q, kpl, vpl, tab, pos,
                                               impl=impl)
                    return o, (kpl, vpl)

                return gpt_block_body(xc, p, eps, nh, hd, attend)

            x, (kpool, vpool) = jax.lax.scan(
                block, x, (stacked, kpool, vpool))
            x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
            last = x[:, -1]
            if "lm_head.weight" in other:
                logits = last @ other["lm_head.weight"]
            else:
                logits = last @ wte.T
            nxt = self._sample_tok(logits, keys, pos + 1, temps,
                                   top_ks, top_ps)
            return kpool, vpool, nxt

        return tick

    def _make_prefill_chunk(self):
        """One fixed-shape suffix-prefill program: process a
        ``prefill_chunk``-token slice of one slot's prompt through
        ``gpt_paged_suffix_apply`` (KV scattered straight into the
        slot's pages; attention reads aliased prefix pages + the
        chunk). The chunk start / true prompt length ride as traced
        scalars, so EVERY chunk of EVERY prompt shares this one
        compiled program — the per-bucket prefill retraces of the
        whole-prompt design collapse to a single trace. The sampled
        token is only meaningful on the final chunk (the host ignores
        it otherwise)."""
        mcfg = self.model_config
        site = self._prefill_site
        chunk = self.prefill_chunk

        from ..models.gpt import gpt_paged_suffix_apply

        def prefill(stacked, other, kpool, vpool, tokens, pos0, true_len,
                    page_row, key, temp, top_k, top_p):
            _recompile.mark_trace(site, tokens, kpool, pos0)
            li = jnp.clip(true_len - 1 - pos0, 0, chunk - 1)
            logits, kpool, vpool = gpt_paged_suffix_apply(
                mcfg, stacked, other, kpool, vpool, tokens, pos0,
                true_len, page_row, li)
            tok0 = self._sample_tok(logits, key[None], true_len[None],
                                    temp, top_k, top_p)
            return kpool, vpool, tok0

        return prefill
