"""Continuous-batching decode engine over the paged KV cache.

The dense ``GPT.generate`` path is one jitted prefill+scan program per
request batch: every admitted prompt pays ``S_max`` of cache HBM,
nobody can join or leave mid-decode, and mixed prompt lengths force
padding waste or a retrace. This engine restructures serving the way
the roadmap's cross-replica-sharding paper restructures the weight
update — so the hardware never idles on work another request could
fill:

- **Fixed-shape decode tick.** One jitted program over ``num_slots``
  cache slots advances every resident request by one token per call.
  The program shape never depends on which slots are live, so it
  traces exactly once (asserted via ``profiler.recompile`` telemetry).
- **Continuous admission / eviction.** Requests are admitted into free
  slots as others finish; EOS and max-token eviction return pages to
  the pool mid-flight. Prefill runs in a small set of length buckets
  (bounded, visible retraces), writing KV straight into the slot's
  pages.
- **Deferred host sync** (the PR-3 async-pipeline idiom): each tick's
  token vector stays an unmaterialized device array; the host
  dispatches tick N+1 (and prefills, via donated pool buffers) before
  materializing tick N, keeping up to ``max_inflight`` ticks in
  flight. Scheduling that must be host-deterministic (positions, page
  growth, max-token stops) never reads device data; only EOS discovery
  rides the lagged window.
- **Exhaustion → preemption.** If the pool cannot grow a slot, the
  engine drains, retries, then preempts the youngest request: its
  generated prefix is requeued as a longer prompt. Re-prefill is
  bitwise-equivalent to having continued (prefill and decode share the
  same compiled math), and sampling keys are folded per absolute
  position, so a preempted request's tokens do not depend on
  scheduling.

Greedy paged decode is **bitwise identical** to the dense
``generate()`` on the same weights whenever the slot capacity
``pages_per_slot * page_size`` equals the dense path's
``prompt + max_new_tokens`` (the attention reduction length must match
exactly — zero-tail padding is not bitwise-neutral). The
``GPT.generate(paged=True)`` wrapper picks a divisor page size so this
holds by construction; tests/test_serving.py pins it.

Profiler signals: ``serving/queue_depth``, ``serving/active_slots``,
``serving/page_util``, ``serving/ttft_ms`` (histogram),
``serving/tokens_per_sec``, ``serving/tokens_generated``,
``serving/prefills``, ``serving/ticks``, ``serving/preemptions``,
``serving/requests_finished``, ``serving/token_syncs``.
"""
from __future__ import annotations

import time
import warnings
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..profiler import recompile as _recompile
from ..profiler import registry as _registry
from .paged_cache import PagePool

__all__ = ["ServingConfig", "ServingEngine", "Request"]


@contextmanager
def _quiet_donation():
    """CPU jax may decline buffer donation for the page pools; the
    fallback copy is correct, just slower — don't spam the log for it.
    Scoped to the engine's own dispatches: a global filter would also
    swallow the training stack's donation-failure warnings (a real perf
    signal in hybrid.py's jitted step)."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    """Engine knobs. Pool sizing math: the pool holds
    ``num_pages - 1`` allocatable pages (page 0 is the null page) of
    ``page_size`` tokens each, shared by ``num_slots`` resident
    requests of at most ``pages_per_slot`` pages
    (``slot_capacity = pages_per_slot * page_size`` tokens). Sizing
    ``num_pages - 1 < num_slots * pages_per_slot`` oversubscribes the
    pool — legal, served by preemption when it binds."""

    num_slots: int = 8
    page_size: int = 16
    pages_per_slot: int = 0          # default: ceil(max_seq_len / page_size)
    num_pages: int = 0               # default: full residency + null page
    prefill_buckets: Tuple[int, ...] = ()   # default: pow2 ladder to capacity
    max_inflight: int = 2            # unmaterialized decode ticks kept in flight
    decode: str = "greedy"           # 'greedy' | 'sampling'
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    attention_impl: str = "xla"      # 'xla' | 'pallas' (ops/paged_attention)


@dataclass
class Request:
    rid: int
    prompt: np.ndarray               # current prompt (grows on preemption)
    max_new: int                     # tokens still wanted (shrinks on preempt)
    key: np.ndarray                  # uint32[2] sampling key (absolute-pos folds)
    out: List[int] = field(default_factory=list)
    done: bool = False
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    orig_prompt_len: int = 0         # for result accounting across preemption


class _Inflight:
    __slots__ = ("tok", "meta")

    def __init__(self, tok, meta):
        self.tok = tok               # device int32 array
        self.meta = meta             # [(index_into_tok, slot, rid)]


class ServingEngine:
    """Continuous-batching serving runtime for a dense ``GPT`` model.

    ::

        eng = ServingEngine(model, ServingConfig(num_slots=8))
        rid = eng.submit(prompt_ids, max_new_tokens=32)
        out = eng.run()[rid]          # np.int32 generated ids
    """

    def __init__(self, model, config: Optional[ServingConfig] = None):
        cfg = config or ServingConfig()
        mcfg = model.config
        if cfg.decode not in ("greedy", "sampling"):
            raise ValueError(f"unknown decode mode {cfg.decode!r}")
        self.config = cfg
        self.model_config = mcfg
        self._stacked, self._other = model._decode_state()
        self._dtype = self._other["embeddings.wte.weight"].dtype
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        ps = cfg.page_size
        pages_per_slot = cfg.pages_per_slot or -(-mcfg.max_seq_len // ps)
        num_pages = cfg.num_pages or cfg.num_slots * pages_per_slot + 1
        self.pool = PagePool(mcfg.num_layers, num_pages, ps, nh, hd,
                             cfg.num_slots, pages_per_slot,
                             dtype=self._dtype)
        cap = self.pool.slot_capacity
        if cfg.prefill_buckets:
            buckets = sorted(set(int(b) for b in cfg.prefill_buckets))
        else:
            buckets, b = [], ps
            while b < cap:
                buckets.append(b)
                b *= 2
            buckets.append(cap)
        if buckets[-1] < cap:
            buckets.append(cap)
        self.prefill_buckets = buckets
        b_slots = cfg.num_slots
        # host scheduling state (never reads device data)
        self._slot_rid: List[Optional[int]] = [None] * b_slots
        self._slot_len = np.zeros(b_slots, np.int32)      # tokens in cache
        self._slot_dispatched = np.zeros(b_slots, np.int64)  # tokens emitted
        self._slot_admit_seq = np.zeros(b_slots, np.int64)
        self._admit_seq = 0
        self._queue: deque[Request] = deque()
        self._requests: Dict[int, Request] = {}
        self._next_rid = 0
        self._inflight: deque[_Inflight] = deque()
        self.max_inflight_seen = 0
        # device state
        self._last_tok = jnp.zeros((b_slots,), jnp.int32)
        self._keys = np.zeros((b_slots, 2), np.uint32)
        self._base_key = np.asarray(jax.random.PRNGKey(cfg.seed))
        # compiled programs: ONE tick site (asserted single-trace) and one
        # prefill site shared by all buckets (retraces == extra buckets)
        self._tick_site = _recompile.unique_site("serving.tick")
        self._prefill_site = _recompile.unique_site("serving.prefill")
        self._tick = jax.jit(self._make_tick(), donate_argnums=(2, 3))
        self._prefills: Dict[int, object] = {}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               key: Optional[np.ndarray] = None) -> int:
        """Queue one request. Returns its request id."""
        p = np.asarray(prompt_ids, np.int32).reshape(-1)
        t0 = p.shape[0]
        cap = self.pool.slot_capacity
        if t0 < 1 or max_new_tokens < 1:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        if t0 + max_new_tokens - 1 > cap:
            raise ValueError(
                f"prompt {t0} + {max_new_tokens} new tokens needs "
                f"{t0 + max_new_tokens - 1} cache positions; slot capacity "
                f"is {cap} (pages_per_slot * page_size) — raise "
                "pages_per_slot or page_size")
        if self.pool.pages_for(t0 + max_new_tokens - 1) > \
                self.pool.allocator.num_pages - 1:
            raise ValueError("request exceeds the whole page pool")
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = np.asarray(jax.random.fold_in(self._base_key, rid))
        req = Request(rid=rid, prompt=p, max_new=int(max_new_tokens),
                      key=np.asarray(key, np.uint32),
                      submit_t=time.perf_counter(), orig_prompt_len=t0)
        self._requests[rid] = req
        self._queue.append(req)
        return rid

    def step(self) -> bool:
        """One scheduler iteration: bound the in-flight window, admit
        into free slots, grow pages (preempting on exhaustion), dispatch
        one decode tick. Returns whether any device work was dispatched."""
        self._drain(self.config.max_inflight)
        dispatched = self._admit()
        self._grow_pages()
        dispatched = self._dispatch_tick() or dispatched
        reg = _registry()
        reg.gauge("serving/queue_depth").set(float(len(self._queue)))
        reg.gauge("serving/active_slots").set(
            float(sum(r is not None for r in self._slot_rid)))
        reg.gauge("serving/page_util").set(self.pool.allocator.utilization())
        return dispatched

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until every submitted request finished; returns
        {rid: generated ids np.int32[<=max_new]}."""
        t0 = time.perf_counter()
        tokens0 = self._tokens_done()
        while True:
            progressed = self.step()
            if not progressed:
                if self._inflight:
                    self._drain(0)
                    continue
                if all(r is None for r in self._slot_rid):
                    if not self._queue:
                        break
                    # every slot free, window empty, still can't admit
                    raise RuntimeError(
                        "serving queue stalled: page pool too small for "
                        "the queued prompt")
                raise RuntimeError(
                    "serving scheduler deadlock: resident requests but "
                    "nothing dispatchable")
        wall = max(time.perf_counter() - t0, 1e-9)
        done = self._tokens_done() - tokens0
        _registry().gauge("serving/tokens_per_sec").set(done / wall)
        return {rid: np.asarray(r.out, np.int32)
                for rid, r in self._requests.items() if r.done}

    def drain(self, target: int = 0) -> None:
        """Materialize in-flight ticks until at most ``target`` remain."""
        self._drain(target)

    def idle(self) -> bool:
        """True when nothing is queued, resident, or in flight."""
        return (not self._queue and not self._inflight
                and all(r is None for r in self._slot_rid))

    def reset_results(self) -> None:
        """Forget finished requests (long-running host keeps memory flat)."""
        self._requests = {rid: r for rid, r in self._requests.items()
                          if not r.done}

    def _tokens_done(self) -> int:
        return sum(len(r.out) for r in self._requests.values())

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _drain(self, target: int) -> None:
        """Materialize in-flight ticks oldest-first until at most
        ``target`` remain. The ONLY place device data reaches the host."""
        while len(self._inflight) > target:
            ent = self._inflight.popleft()
            toks = np.asarray(ent.tok)
            _registry().counter("serving/token_syncs").add(1)
            now = time.perf_counter()
            for idx, slot, rid in ent.meta:
                req = self._requests[rid]
                if req.done:
                    continue        # EOS discovered while this was in flight
                tok = int(toks[idx])
                req.out.append(tok)
                _registry().counter("serving/tokens_generated").add(1)
                if req.first_token_t is None:
                    req.first_token_t = now
                    _registry().histogram("serving/ttft_ms").observe(
                        (now - req.submit_t) * 1000.0)
                eos = self.config.eos_token_id
                # max_new counts tokens wanted since the LAST (re)queue —
                # preemption moved earlier output into the prompt and
                # shrank max_new to the remainder
                if (eos is not None and tok == eos) or \
                        len(req.out) >= req.max_new:
                    self._finish(slot, rid)

    def _finish(self, slot: int, rid: int) -> None:
        req = self._requests[rid]
        req.done = True
        # fold the preemption-era prefix back into the result
        extra = req.prompt[req.orig_prompt_len:]
        if extra.size:
            req.out = list(extra) + req.out
        if self._slot_rid[slot] == rid:
            self.pool.release_slot(slot)
            self._slot_rid[slot] = None
            self._slot_len[slot] = 0
        _registry().counter("serving/requests_finished").add(1)

    def _admit(self) -> bool:
        any_dispatch = False
        free = [s for s, r in enumerate(self._slot_rid) if r is None]
        while self._queue and free:
            req = self._queue[0]
            t0 = req.prompt.shape[0]
            slot = free[-1]
            if not self.pool.grow_slot(slot, self.pool.pages_for(t0)):
                break               # pool exhausted; wait for evictions
            self._queue.popleft()
            free.pop()
            self._slot_rid[slot] = req.rid
            self._slot_len[slot] = t0
            self._slot_dispatched[slot] = 1
            self._admit_seq += 1
            self._slot_admit_seq[slot] = self._admit_seq
            self._dispatch_prefill(slot, req)
            any_dispatch = True
        return any_dispatch

    def _bucket_for(self, t0: int) -> int:
        for b in self.prefill_buckets:
            if b >= t0:
                return b
        raise ValueError(f"prompt length {t0} exceeds largest prefill "
                         f"bucket {self.prefill_buckets[-1]}")

    def _dispatch_prefill(self, slot: int, req: Request) -> None:
        t0 = req.prompt.shape[0]
        bucket = self._bucket_for(t0)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :t0] = req.prompt
        fn = self._prefills.get(bucket)
        if fn is None:
            fn = self._prefills[bucket] = jax.jit(
                self._make_prefill(bucket), donate_argnums=(2, 3))
        page_ids = np.ascontiguousarray(self.pool.tables[slot])
        with _quiet_donation():
            self.pool.k, self.pool.v, tok0 = fn(
                self._stacked, self._other, self.pool.k, self.pool.v,
                toks, np.int32(t0), page_ids, req.key)
        self._last_tok = self._last_tok.at[slot].set(tok0[0])
        self._keys[slot] = req.key
        self._inflight.append(_Inflight(tok0, [(0, slot, req.rid)]))
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     len(self._inflight))
        _registry().counter("serving/prefills").add(1)

    def _ticking_slots(self) -> List[int]:
        """Slots that should advance this tick: resident, not finished,
        and with emissions still owed. A slot whose final token is
        already dispatched stops ticking immediately (max-token stop is
        host-deterministic); EOS stops lag by <= max_inflight ticks."""
        out = []
        for s, rid in enumerate(self._slot_rid):
            if rid is None:
                continue
            req = self._requests[rid]
            if not req.done and self._slot_dispatched[s] < req.max_new:
                out.append(s)
        return out

    def _grow_pages(self) -> None:
        for s in self._ticking_slots():
            if self._slot_rid[s] is None:
                continue            # freed by an earlier drain/preempt
            need_page = int(self._slot_len[s]) // self.pool.page_size
            if need_page < self.pool.slot_pages(s):
                continue
            if self.pool.grow_slot(s, 1):
                continue
            # exhaustion: learn about in-flight finishes, then retry
            self._drain(0)
            if self._slot_rid[s] is None:
                continue            # this very slot finished in the drain
            if self.pool.grow_slot(s, 1):
                continue
            self._preempt_for(s)

    def _preempt_for(self, needy_slot: int) -> None:
        """Free pages by requeueing the youngest resident request (its
        generated prefix becomes prompt, so no work is redone twice)."""
        live = [s for s in range(self.config.num_slots)
                if self._slot_rid[s] is not None]
        victim = max(live, key=lambda s: self._slot_admit_seq[s])
        rid = self._slot_rid[victim]
        req = self._requests[rid]
        # window was drained in _grow_pages, so req.out is current
        req.prompt = np.concatenate(
            [req.prompt, np.asarray(req.out, np.int32)])
        req.max_new -= len(req.out)
        req.out = []
        self._queue.appendleft(req)
        self.pool.release_slot(victim)
        self._slot_rid[victim] = None
        self._slot_len[victim] = 0
        _registry().counter("serving/preemptions").add(1)
        if victim != needy_slot and self._slot_rid[needy_slot] is not None:
            if not self.pool.grow_slot(needy_slot, 1):
                self._preempt_for(needy_slot)

    def _dispatch_tick(self) -> bool:
        ticking = self._ticking_slots()
        if not ticking:
            return False
        tab = np.ascontiguousarray(self.pool.tables)
        pos = np.ascontiguousarray(self._slot_len)
        keys = np.ascontiguousarray(self._keys)
        with _quiet_donation():
            self.pool.k, self.pool.v, tok = self._tick(
                self._stacked, self._other, self.pool.k, self.pool.v,
                tab, pos, self._last_tok, keys)
        self._last_tok = tok
        meta = [(s, s, self._slot_rid[s]) for s in ticking]
        self._inflight.append(_Inflight(tok, meta))
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     len(self._inflight))
        for s in ticking:
            self._slot_len[s] += 1
            self._slot_dispatched[s] += 1
        _registry().counter("serving/ticks").add(1)
        _registry().gauge("serving/decode_batch").set(float(len(ticking)))
        return True

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _sample_tok(self, logits, keys, positions):
        """Token choice from last-token logits [N, V]. Greedy mirrors
        ops/decoding.greedy_decode (argmax of f32 log_softmax — parity);
        sampling folds each slot's key by the ABSOLUTE position of the
        emitted token, so a request's stream is independent of
        scheduling/preemption."""
        c = self.config
        if c.decode == "greedy":
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return jnp.argmax(lp, axis=-1).astype(jnp.int32)
        from ..ops.decoding import apply_top_k_top_p

        lg = logits.astype(jnp.float32) / jnp.maximum(c.temperature, 1e-6)
        lg = apply_top_k_top_p(lg, c.top_k, c.top_p)
        lp = jax.nn.log_softmax(lg, axis=-1)

        def one(key, pos, row):
            return jax.random.categorical(jax.random.fold_in(key, pos), row)

        return jax.vmap(one)(keys, positions, lp).astype(jnp.int32)

    def _make_tick(self):
        mcfg = self.model_config
        ps = self.pool.page_size
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        eps = mcfg.layer_norm_eps
        nslots = self.config.num_slots
        impl = self.config.attention_impl
        site = self._tick_site

        from ..models.gpt import _ln, gpt_block_body
        from ..ops.paged_attention import paged_decode_attention

        def tick(stacked, other, kpool, vpool, tab, pos, tok, keys):
            _recompile.mark_trace(site, kpool, tab, pos, tok)
            wte = other["embeddings.wte.weight"]
            wpe = other["embeddings.wpe.weight"]
            x = wte[tok[:, None]] + wpe[pos[:, None]]        # [B, 1, h]
            page = tab[jnp.arange(nslots), pos // ps]
            off = pos % ps

            def block(xc, inp):
                p, kpl0, vpl0 = inp

                def attend(q, kk, vv):
                    kpl = kpl0.at[page, off].set(kk[:, 0])
                    vpl = vpl0.at[page, off].set(vv[:, 0])
                    o = paged_decode_attention(q, kpl, vpl, tab, pos,
                                               impl=impl)
                    return o, (kpl, vpl)

                return gpt_block_body(xc, p, eps, nh, hd, attend)

            x, (kpool, vpool) = jax.lax.scan(
                block, x, (stacked, kpool, vpool))
            x = _ln(x, other["ln_f.weight"], other["ln_f.bias"], eps)
            last = x[:, -1]
            if "lm_head.weight" in other:
                logits = last @ other["lm_head.weight"]
            else:
                logits = last @ wte.T
            nxt = self._sample_tok(logits, keys, pos + 1)
            return kpool, vpool, nxt

        return tick

    def _make_prefill(self, bucket: int):
        """Prefill one request (padded to ``bucket``) through the SAME
        dense cached forward as the non-paged path, with the scratch
        cache sized to the slot capacity (reduction-length parity), then
        scatter the computed KV into the slot's pages. Right-padding is
        causal-masked garbage: padded positions write to allocated pages
        but are masked until decode overwrites each one first."""
        mcfg = self.model_config
        cap = self.pool.slot_capacity
        nps = self.pool.pages_per_slot
        ps = self.pool.page_size
        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        L = mcfg.num_layers
        dt = self._dtype
        site = self._prefill_site

        from ..models.gpt import gpt_cached_apply

        def prefill(stacked, other, kpool, vpool, tokens, true_len,
                    page_ids, key):
            _recompile.mark_trace(site, tokens, kpool)
            ck = jnp.zeros((1, L, cap, nh, hd), dt)
            cv = jnp.zeros((1, L, cap, nh, hd), dt)
            logits, ck, cv = gpt_cached_apply(
                mcfg, stacked, other, ck, cv, tokens, 0,
                logits_index=true_len - 1)
            kpages = ck[0].reshape(L, nps, ps, nh, hd)
            vpages = cv[0].reshape(L, nps, ps, nh, hd)
            kpool = kpool.at[:, page_ids].set(kpages)
            vpool = vpool.at[:, page_ids].set(vpages)
            tok0 = self._sample_tok(logits, key[None], true_len[None])
            return kpool, vpool, tok0

        return prefill
