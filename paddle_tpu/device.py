"""paddle.device namespace (reference: python/paddle/device.py).

The reference module multiplexes CUDA/XPU/CPU place selection; here the
accelerator is the TPU and the real logic lives in core/place.py — this
module preserves the importable surface (``paddle.device.set_device`` et
al.) plus the capability probes, which answer for the TPU stack.
"""
from .core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, XPUPlace, device_count, get_device,
    is_compiled_with_tpu, set_device)

__all__ = ["get_cudnn_version", "get_device", "set_device",
           "is_compiled_with_xpu", "is_compiled_with_cuda",
           "is_compiled_with_tpu", "XPUPlace"]


def is_compiled_with_xpu() -> bool:
    """No Baidu-Kunlun XPU in the TPU stack."""
    return False


def is_compiled_with_cuda() -> bool:
    """The TPU build carries no CUDA kernels (the reference's probe keys
    feature fallbacks off this — False routes them to the portable
    path)."""
    return False


def get_cudnn_version():
    """None: no cuDNN in the TPU stack (reference returns None when not
    compiled with CUDA, device.py:72)."""
    return None
