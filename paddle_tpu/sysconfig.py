"""paddle.sysconfig (reference: python/paddle/sysconfig.py —
get_include/get_lib for building C++ extensions against the install)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of native headers (the custom-op C ABI lives in
    utils/custom_op.py's docstring; native sources under native/)."""
    return os.path.join(os.path.dirname(_ROOT), "native", "src")


def get_lib() -> str:
    """Directory containing the built native runtime library."""
    return os.path.join(_ROOT, "_native")
