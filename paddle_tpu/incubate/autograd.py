"""Functional autograd (reference: imperative/partial_grad_engine.cc
double-grad; python/paddle/autograd/functional.py).

These operate on pure functions of Tensors and support arbitrary-order
differentiation by composing jax transforms.
"""
from __future__ import annotations

import jax

from ..framework.tensor import Tensor


def _wrap_fn(fn):
    def pure(*vals):
        outs = fn(*[Tensor(v, stop_gradient=False) for v in vals])
        if isinstance(outs, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in outs)
        return outs._value if isinstance(outs, Tensor) else outs

    return pure


def _vals(xs):
    if isinstance(xs, Tensor):
        return (xs._value,), True
    return tuple(x._value for x in xs), False


def vjp(func, xs, v=None):
    vals, single = _vals(xs)
    out, vjp_fn = jax.vjp(_wrap_fn(func), *vals)
    if v is None:
        import jax.numpy as jnp

        v_val = jnp.ones_like(out)
    else:
        v_val = v._value if isinstance(v, Tensor) else v
    grads = vjp_fn(v_val)
    outs = Tensor(out) if not isinstance(out, tuple) else \
        tuple(Tensor(o) for o in out)
    gs = tuple(Tensor(g) for g in grads)
    return outs, gs[0] if single else gs


def jvp(func, xs, v=None):
    vals, single = _vals(xs)
    if v is None:
        import jax.numpy as jnp

        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vs = (v,) if isinstance(v, Tensor) else tuple(v)
        tangents = tuple(t._value for t in vs)
    out, tangent_out = jax.jvp(_wrap_fn(func), vals, tangents)
    outs = Tensor(out) if not isinstance(out, tuple) else \
        tuple(Tensor(o) for o in out)
    touts = Tensor(tangent_out) if not isinstance(tangent_out, tuple) else \
        tuple(Tensor(t) for t in tangent_out)
    return outs, touts


def grad(func, argnums=0):
    """Higher-order-capable functional grad."""
    g = jax.grad(_wrap_fn(func), argnums=argnums)

    def wrapper(*xs):
        vals = tuple(x._value if isinstance(x, Tensor) else x for x in xs)
        out = g(*vals)
        if isinstance(out, tuple):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)

    return wrapper


def Jacobian(func, xs, is_batched=False):  # noqa: N802
    vals, single = _vals(xs)
    jac = jax.jacrev(_wrap_fn(func))(*vals)
    return Tensor(jac)


def Hessian(func, xs, is_batched=False):  # noqa: N802
    vals, single = _vals(xs)
    hes = jax.hessian(_wrap_fn(func))(*vals)
    return Tensor(hes)
