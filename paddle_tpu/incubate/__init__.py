"""Incubating APIs (reference: python/paddle/fluid/incubate/).

Hosts the functional autograd surface (higher-order grads via jax
composition — the eager tape is first-order; see autograd.tape.grad).
"""
from . import autograd  # noqa: F401
