"""Probability distributions (reference: python/paddle/distribution.py —
Distribution:41, Uniform:168, Normal:390, Categorical:640; the v2.0 API:
sample / entropy / log_prob / probs / kl_divergence).

TPU-native: sampling draws from the framework RNG stream
(core/rng.py — the same stream checkpoints/elastic restore), math is
jnp through the eager tape so log_prob/entropy are differentiable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core import rng as _rng
from .framework.tensor import Tensor
from .tensor._helper import apply

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_tensor(x):
    """Keep user Tensors intact (grads flow to distribution params —
    reference parameters are Variables too); wrap raw scalars/arrays."""
    return x if isinstance(x, Tensor) else Tensor(
        jnp.asarray(x, jnp.float32))


class Distribution:
    """Base (reference: distribution.py:41)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) (reference: distribution.py:168)."""

    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)

    def sample(self, shape=(), seed=0):
        key = _rng.next_key()
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.low._value.shape, self.high._value.shape)
        u = jax.random.uniform(key, shape, jnp.float32)
        return Tensor(self.low._value
                      + u * (self.high._value - self.low._value))

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo),
                     self.low, self.high)

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            lp = -jnp.log(hi - lo)
            return jnp.where(inside, lp, -jnp.inf)

        return apply(f, _as_tensor(value), self.low, self.high)

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))


class Normal(Distribution):
    """N(loc, scale) (reference: distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def sample(self, shape=(), seed=0):
        key = _rng.next_key()
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)
        z = jax.random.normal(key, shape, jnp.float32)
        return Tensor(self.loc._value + z * self.scale._value)

    def entropy(self):
        return apply(
            lambda s: 0.5 + 0.5 * np.log(2 * np.pi) + jnp.log(s),
            self.scale)

    def log_prob(self, value):
        def f(v, mu, s):
            var = s * s
            return -((v - mu) ** 2) / (2 * var) - jnp.log(s) \
                - 0.5 * np.log(2 * np.pi)

        return apply(f, _as_tensor(value), self.loc, self.scale)

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference
        distribution.py:~600 kl_divergence)."""
        def f(mu0, s0, mu1, s1):
            var_ratio = (s0 / s1) ** 2
            t1 = ((mu0 - mu1) / s1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))

        return apply(f, self.loc, self.scale,
                     other.loc, other.scale)


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference:
    distribution.py:640 — takes logits, normalizes internally)."""

    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)

    def _probs(self):
        # reference semantics: logits are unnormalized PROBABILITIES
        # (non-negative weights); normalize by their sum
        w = self.logits._value
        return w / jnp.sum(w, axis=-1, keepdims=True)

    def sample(self, shape=()):
        key = _rng.next_key()
        p = self._probs()
        logp = jnp.log(jnp.maximum(p, 1e-38))
        return Tensor(jax.random.categorical(
            key, logp, shape=tuple(shape) + logp.shape[:-1]))

    def entropy(self):
        def f(w):
            p = w / jnp.sum(w, axis=-1, keepdims=True)
            return -jnp.sum(p * jnp.log(jnp.maximum(p, 1e-38)), axis=-1)

        return apply(f, self.logits)

    def probs(self, value):
        def f(w, idx):
            p = w / jnp.sum(w, axis=-1, keepdims=True)
            return jnp.take_along_axis(
                p, idx.astype(jnp.int32)[..., None], -1)[..., 0]

        return apply(f, self.logits, _as_tensor(value))

    def log_prob(self, value):
        return apply(lambda p: jnp.log(jnp.maximum(p, 1e-38)),
                     self.probs(value))

    def kl_divergence(self, other):
        def f(w0, w1):
            p = w0 / jnp.sum(w0, axis=-1, keepdims=True)
            q = w1 / jnp.sum(w1, axis=-1, keepdims=True)
            return jnp.sum(p * (jnp.log(jnp.maximum(p, 1e-38))
                                - jnp.log(jnp.maximum(q, 1e-38))), -1)

        return apply(f, self.logits, other.logits)
