"""paddle.onnx equivalent (reference: python/paddle/onnx/export.py —
a 60-line shim that DELEGATES to the external ``paddle2onnx`` package).

The same delegation pattern: ``export`` always produces the portable
jax.export/StableHLO artifact (runnable via paddle_tpu.inference — the
TPU-native interchange format), and additionally emits an ONNX file when
an ``onnx``+converter stack is importable (absent in this environment,
exactly as paddle2onnx is absent from the reference repo itself).
"""
from __future__ import annotations

from ..jit.api import save as _jit_save

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    """Export ``layer`` for interchange.

    Always writes the StableHLO portable artifact (path.pdmodel.bin —
    load with paddle_tpu.inference.Predictor or jax.export). When the
    ``onnx`` package is importable, also attempts an ONNX conversion at
    ``path.onnx`` (reference behavior: delegate to the converter
    package; raise the same ImportError style when missing is avoided —
    the StableHLO artifact is the primary product here).
    """
    if input_spec is None:
        raise ValueError("paddle_tpu.onnx.export requires input_spec")
    _jit_save(layer, path, input_spec=input_spec)
    try:
        import onnx  # noqa: F401
    except ImportError:
        return path + ".pdmodel.bin"
    # converter stacks (jaxonnxruntime etc.) are not bundled; the
    # StableHLO artifact remains the canonical export
    return path + ".pdmodel.bin"
