"""The hardened training loop: bad-step guard + rollback, graceful
preemption, step watchdog, degraded restore, retried data loading.

``ResilientRunner`` wraps a ``HybridPipelineTrainer`` (or anything with
the same ``step``/``device_state``/``load_device_state`` surface)
behind an ``ElasticTrainer`` and runs the loop the ISSUE tentpole
specifies:

  1. **bad-step guard** — the trainer's compiled finite check
     (``guard_bad_steps``) skips the update on a NaN/Inf step; the
     runner counts consecutive bad steps and after
     ``bad_step_limit`` of them ROLLS BACK to the newest readable
     committed checkpoint and re-seeds the data cursor past the
     offending batches (they land in a persisted skip set, so replay —
     and any later restart — never feeds them again).
  2. **graceful preemption** — SIGTERM/SIGINT set a flag; the in-flight
     step finishes, a synchronous committed checkpoint lands, and
     ``run`` returns a RunResult carrying the resumable exit code.
  3. **step watchdog** — a monitor thread that dumps live stacks +
     profiler span state on a hung step and optionally aborts so the
     elastic restart path takes over (resilience/watchdog.py).
  4. **degraded restore** — resume walks back past corrupt newest
     steps (checkpoint.restore_degraded) instead of dying.
  5. data loading rides ``utils.retry`` with exponential backoff.

Every recovery event moves a profiler counter: ``resilience/
steps_skipped``, ``resilience/rollbacks``, ``resilience/
restore_fallbacks``, ``resilience/preemptions``, ``resilience/
data_retries``, ``resilience/watchdog_fires``.

Determinism contract: with a fixed ``ChaosPlan``, a run that is
preempted, corrupted, and restarted produces the SAME per-step losses
as an uninterrupted run (the chaos e2e test asserts this bitwise).

Cross-host agreement (ISSUE 13 — retires the PR 2 residue): with
``ResilienceConfig(consensus=...)`` set (a
``distributed.consensus.Consensus`` over the job's shared board), the
K-streak verdict becomes a MESH-WIDE agreement instead of a per-rank
decision. The rank that hits the streak opens a ``resil`` vote (verdict
``rollback`` — or ``abort`` when it has nothing restorable and no
guard); healthy ranks notice the open round at their next step
boundary (one directory poll per step — free next to a train step),
drain their window, and join with verdict ``healthy`` plus their own
partial bad-cursor streak. The published decision carries the UNION of
every rank's poisoned cursors, so all ranks re-seed identically and
the data timeline stays in lockstep; an agreed abort raises on every
rank instead of leaving N-1 processes training into a dead mesh. A
rank whose fault only IT can see (a local NaN injection, a local
loader giving up) therefore takes the whole mesh back to the same
committed step — chaos-tested on the real process mesh
(tests/multihost/test_resilience_mesh.py). Leases ride a heartbeat
thread for the duration of ``run`` (compile stalls must not mark the
rank dead). The restored step is PART of the agreement: each vote
carries the rank's newest commit at or before its own bad-streak
start, the reducer publishes the min, and every rank resumes capped at
that target (``ElasticTrainer.resume(max_step=...)``) — a rank that
committed a checkpoint after the proposer's streak began therefore
walks back WITH the mesh instead of silently resuming younger state
(state-lockstep; previously the shared save schedule was assumed to
make the newest commit agree, which drifts exactly when ranks detect
the streak at different steps).
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

from ..distributed.elastic import ElasticTrainer
from ..profiler.metrics import registry as _registry
from ..utils.retry import retry
from .preemption import PREEMPT_EXIT_CODE, PreemptionHandler
from .watchdog import StepWatchdog

__all__ = ["ResilienceConfig", "ResilientRunner", "RunResult"]


def _resilience_reducer(votes):
    """The ``resil`` vote's deterministic reduce: the mesh verdict is
    the most severe any rank reported (abort > rollback > healthy), the
    poisoned-cursor set is the union — every rank must blocklist every
    rank's bad batches or the data timelines diverge — and the restore
    ``target`` is the MIN over the ranks' ``restorable`` steps (each
    rank's newest commit at or before its own bad-streak start; -1 when
    it has none). The min is the newest step safe for EVERY rank:
    without it, a rank that committed a checkpoint after the proposer's
    streak began would resume from younger state than the proposer and
    the mesh would leave state-lockstep. ``v.get`` keeps rounds with
    older peers (votes without the field) decidable."""
    verdicts = [v["verdict"] for v in votes.values()]
    verdict = "abort" if "abort" in verdicts else (
        "rollback" if "rollback" in verdicts else "healthy")
    cursors = sorted({int(c) for v in votes.values()
                      for c in v["bad_cursors"]})
    rest = [int(v.get("restorable", -1)) for v in votes.values()]
    rest = [r for r in rest if r >= 0]
    return {"verdict": verdict, "bad_cursors": cursors,
            "target": min(rest) if rest else -1}


class ResilienceConfig:
    """Knobs of the hardened loop (README "Resilience" documents them).

    bad_step_limit:         consecutive guarded-bad steps before a
                            rollback (K).
    watchdog_timeout_s:     None disables the watchdog.
    watchdog_first_grace_s: extra allowance for a lifetime's first step
                            (jit compile); default 10× the timeout.
    watchdog_jitter:        deadline jitter fraction (fleet de-sync).
    watchdog_abort:         hard-exit on fire (WATCHDOG_EXIT_CODE).
    data_retry_attempts /   retry-with-exponential-backoff policy for
    data_retry_base_delay:  data_fn calls (utils.retry).
    verify_restore:         CRC-verify shards on resume (the walk-back
                            can only SEE silent corruption when on).
    raise_on_preempt:       raise PreemptedError after the preemption
                            checkpoint commits, instead of returning a
                            RunResult with preempted=True (default).
    consensus:              a distributed.consensus.Consensus over the
                            job's shared board — K-streak rollback and
                            abort become mesh-wide agreements (module
                            docstring). None (default) keeps the
                            host-local single-process behavior.

    Async step pipeline (distributed/elastic.py docstring; README
    "Async step pipeline" has the guard/rollback interaction table):

    async_dispatch:         defer loss AND guard-verdict syncs behind a
                            bounded in-flight window so dispatch of
                            step N+1 overlaps execution of step N. The
                            window only opens once a COMMITTED
                            checkpoint exists: a K-streak rollback with
                            younger in-flight steps restores that
                            checkpoint (state, RNG, cursor), which is
                            what keeps deferred-mode loss curves
                            bitwise-identical to synchronous mode.
    sync_interval:          materialize the window at least this often.
    max_inflight:           window size (default 2 steps).
    prefetch_depth:         background input prefetch (+H2D staging)
                            depth; 0 disables. Rollback invalidates
                            every in-flight prefetched batch; the
                            persisted skipped_cursors blocklist is
                            honored before staging.
    snapshot_async /        streamed checkpoint snapshots: D2H in
    snapshot_chunk_bytes:   bounded chunks on the writer thread, gated
                            before the next dispatch (checkpoint.save).
    """

    def __init__(self,
                 bad_step_limit: int = 3,
                 watchdog_timeout_s: Optional[float] = None,
                 watchdog_first_grace_s: Optional[float] = None,
                 watchdog_jitter: float = 0.1,
                 watchdog_abort: bool = False,
                 watchdog_dump_file: Optional[str] = None,
                 watchdog_seed: int = 0,
                 data_retry_attempts: int = 4,
                 data_retry_base_delay: float = 0.05,
                 data_retry_max_delay: float = 5.0,
                 data_retry_jitter: float = 0.0,
                 verify_restore: bool = True,
                 raise_on_preempt: bool = False,
                 async_dispatch: bool = False,
                 sync_interval: int = 8,
                 max_inflight: int = 2,
                 prefetch_depth: int = 0,
                 snapshot_async: bool = False,
                 snapshot_chunk_bytes: Optional[int] = None,
                 consensus=None):
        if bad_step_limit < 1:
            raise ValueError("bad_step_limit must be >= 1")
        self.consensus = consensus
        self.bad_step_limit = int(bad_step_limit)
        self.watchdog_timeout_s = watchdog_timeout_s
        self.watchdog_first_grace_s = watchdog_first_grace_s if \
            watchdog_first_grace_s is not None else (
                10.0 * watchdog_timeout_s if watchdog_timeout_s else 0.0)
        self.watchdog_jitter = watchdog_jitter
        self.watchdog_abort = watchdog_abort
        self.watchdog_dump_file = watchdog_dump_file
        self.watchdog_seed = watchdog_seed
        self.data_retry_attempts = int(data_retry_attempts)
        self.data_retry_base_delay = float(data_retry_base_delay)
        self.data_retry_max_delay = float(data_retry_max_delay)
        self.data_retry_jitter = float(data_retry_jitter)
        self.verify_restore = bool(verify_restore)
        self.raise_on_preempt = bool(raise_on_preempt)
        self.async_dispatch = bool(async_dispatch)
        self.sync_interval = max(1, int(sync_interval))
        self.max_inflight = max(1, int(max_inflight))
        self.prefetch_depth = max(0, int(prefetch_depth))
        self.snapshot_async = bool(snapshot_async)
        self.snapshot_chunk_bytes = snapshot_chunk_bytes


class RunResult:
    """What a resilient run lifetime produced.

    losses:      {step: loss} for every step this LIFETIME executed and
                 kept (rollback-discarded steps are removed).
    preempted:   True when the run stopped on a preemption request
                 after committing its checkpoint; ``exit_code`` is then
                 the resumable status (75/EX_TEMPFAIL) a worker should
                 exit with so the supervisor reschedules it.
    completed:   reached total_steps.
    """

    def __init__(self, losses: Dict[int, float], start_step: int,
                 final_step: int, total_steps: int, preempted: bool,
                 rollbacks: int):
        self.losses = losses
        self.start_step = start_step
        self.final_step = final_step
        self.total_steps = total_steps
        self.preempted = preempted
        self.rollbacks = rollbacks

    @property
    def completed(self) -> bool:
        return not self.preempted and self.final_step >= self.total_steps

    @property
    def exit_code(self) -> int:
        return PREEMPT_EXIT_CODE if self.preempted else 0

    def loss_list(self):
        """Losses as a dense list ordered by step (steps this lifetime)."""
        return [self.losses[s] for s in sorted(self.losses)]


class ResilientRunner:
    def __init__(self, trainer, ckpt_dir: str, save_interval: int = 100,
                 keep: int = 3, config: Optional[ResilienceConfig] = None,
                 chaos=None):
        self.config = config or ResilienceConfig()
        self.chaos = chaos
        self.elastic = ElasticTrainer(
            trainer, ckpt_dir, save_interval=save_interval, keep=keep,
            degraded_restore=True,
            verify_restore=self.config.verify_restore,
            snapshot_async=self.config.snapshot_async,
            snapshot_chunk_bytes=self.config.snapshot_chunk_bytes)
        self.trainer = trainer
        self.preemption = PreemptionHandler()
        # cursors whose batches poisoned a rollback — never fed again;
        # persisted in every checkpoint's meta so restarts keep them
        self._skips: set = set()
        # the active input prefetcher (async pipeline), exposed for the
        # chaos tests' in-flight-discard assertions
        self.prefetcher = None

    # -- helpers -----------------------------------------------------------
    def _extra_meta(self) -> dict:
        return {"skipped_cursors": sorted(self._skips)}

    def _merge_resumed_skips(self) -> None:
        self._skips.update(
            int(c) for c in self.elastic.last_meta.get(
                "skipped_cursors", []))

    def _advance_past_skips(self) -> None:
        el = self.elastic
        while el.data_cursor in self._skips:
            el.data_cursor += 1

    def _fetch(self, data_fn, cursor: int):
        cfg = self.config
        reg = _registry()

        def _note(i, e, d):
            reg.counter("resilience/data_retries").add(1)

        return retry(lambda: data_fn(cursor),
                     attempts=cfg.data_retry_attempts,
                     base_delay=cfg.data_retry_base_delay,
                     max_delay=cfg.data_retry_max_delay,
                     jitter=cfg.data_retry_jitter,
                     seed=cursor,          # deterministic per batch
                     on_retry=_note)

    def _restorable(self, streak_start: int) -> int:
        """Newest committed step at or before this rank's bad-streak
        start (-1 when none): the restore point this rank can take
        without resuming state younger than the streak's first poisoned
        batch. Cast into the ``resil`` vote; the reducer mins it across
        ranks so every rank restores the SAME step (state-lockstep)."""
        mgr = self.elastic.manager
        mgr.wait()            # an in-flight save must count or not, not race
        from ..distributed.checkpoint import all_steps
        steps = [s for s in all_steps(mgr.directory)
                 if s <= streak_start]
        return steps[-1] if steps else -1

    def _mesh_agree(self, verdict: str, cursors,
                    restorable: int = -1) -> dict:
        """One ``resil`` agreement round (module docstring): cast this
        rank's verdict + poisoned cursors + newest safely-restorable
        step, adopt the published decision. Raises on an agreed abort —
        EVERY rank raises, which is the point (no survivor trains into
        a dead mesh)."""
        cons = self.config.consensus
        reg = _registry()
        dec = cons.decide(
            "resil",
            {"verdict": verdict,
             "bad_cursors": sorted(int(c) for c in cursors),
             "restorable": int(restorable)},
            reducer=_resilience_reducer)
        reg.counter("resilience/mesh_agreements").add(1)
        if dec.value["verdict"] == "abort":
            reg.counter("resilience/mesh_aborts").add(1)
            from ..profiler import events as _pevents
            from ..profiler import sink as _psink

            _pevents.emit("rollback", mesh_abort=True,
                          participants=dec.participants,
                          missing=dec.missing)
            _pevents.dump_flight("mesh-abort")
            _psink.flush_active("rollback")
            raise RuntimeError(
                f"mesh-wide abort agreed (resil#{dec.epoch}): a rank "
                f"hit its bad-step limit with no restorable checkpoint "
                f"and no guard; participants={dec.participants} "
                f"missing={dec.missing}")
        if dec.value["verdict"] == "rollback":
            reg.counter("resilience/mesh_rollbacks").add(1)
        return dec.value

    def _rollback(self, bad_cursors, guarded: bool,
                  target: Optional[int] = None) -> int:
        """K consecutive bad steps: restore the newest readable
        committed checkpoint — capped at the mesh-agreed ``target``
        step when a consensus round produced one (>= 0), so every rank
        lands on the SAME restore point regardless of how far past the
        streak start it had committed — and blocklist the poisoned
        cursors. Returns the step to continue from. With no committed
        checkpoint yet, a GUARDED trainer just continues past the bad
        batches (the compiled guard kept the weights clean; the cursors
        stay blocklisted for any future replay) — an UNGUARDED one has
        already taken the poisoned updates with nothing to restore, so
        the only honest move is to fail loudly.

        Before the restore, the flight recorder dumps (reason
        "rollback") and the active sink flushes: the window of metric
        deltas + events leading INTO the bad streak is the post-mortem
        evidence, and the restore is about to overwrite the live state
        it describes."""
        from ..profiler import events as _pevents
        from ..profiler import sink as _psink

        el = self.elastic
        _registry().counter("resilience/rollbacks").add(1)
        _pevents.emit("rollback", bad_cursors=sorted(bad_cursors))
        _pevents.dump_flight("rollback")
        _psink.flush_active("rollback")
        self._skips.update(bad_cursors)
        el.manager.wait()              # never restore under an async save
        cap = int(target) if target is not None and int(target) >= 0 \
            else None
        newest = el.manager.latest_step()
        if cap is not None and newest is not None:
            from ..distributed.checkpoint import all_steps
            elig = [s for s in all_steps(el.manager.directory)
                    if s <= cap]
            newest = elig[-1] if elig else None
        if newest is None:
            if not guarded:
                raise RuntimeError(
                    f"{len(bad_cursors)} consecutive non-finite steps "
                    "on a trainer WITHOUT guard_bad_steps and no "
                    "committed checkpoint to roll back to: the weights "
                    "are poisoned and unrecoverable. Enable "
                    "guard_bad_steps or checkpoint before the first "
                    "fault window.")
            return -1                  # continue in place
        step = el.resume(max_step=cap)
        self._merge_resumed_skips()
        return step

    # -- the loop ----------------------------------------------------------
    def run(self, data_fn, total_steps: int, on_step=None) -> RunResult:
        """The hardened loop, with the async step pipeline when the
        config enables it: dispatched steps park their device loss AND
        guard verdict in a bounded in-flight window; the per-step
        bad-step/rollback/save logic runs at materialization time, in
        step order, exactly as the synchronous loop would have run it.
        The window only opens once a committed checkpoint exists — a
        K-streak detected with younger steps already dispatched rolls
        back to that checkpoint (restoring state, RNG and data cursor),
        which discards the younger in-flight timeline deterministically
        and keeps the loss curve bitwise-reproducible.

        NOTE: ElasticTrainer.run has the plain (no-resilience) copy of
        this window/drain/prefetch/gate sequencing — a semantic change
        to the window in either loop almost certainly needs the same
        change in the other (its run() docstring carries the same
        cross-reference)."""
        cfg = self.config
        el = self.elastic
        tr = self.trainer
        chaos = self.chaos
        cons = cfg.consensus
        reg = _registry()
        guarded = bool(getattr(tr, "guard_bad_steps", False))
        # deferred verdicts need the PER-STEP device scalar; a guarded
        # trainer without the accessor must run with a closed window —
        # the `last_step_ok` property only reads the LATEST dispatched
        # step's verdict, which is the right step only when the drain
        # happens immediately after its dispatch
        get_ok = getattr(tr, "last_step_ok_device", None)
        can_defer = not guarded or get_ok is not None
        fetch = chaos.wrap_data_fn(data_fn) if chaos is not None \
            else data_fn

        handler = self.preemption
        handler.clear()
        handler.install()
        wd = None
        if cfg.watchdog_timeout_s:
            wd = StepWatchdog(cfg.watchdog_timeout_s,
                              jitter_frac=cfg.watchdog_jitter,
                              abort=cfg.watchdog_abort,
                              dump_file=cfg.watchdog_dump_file,
                              seed=cfg.watchdog_seed).start()
            # the checkpoint restore below is as slow as a first compile
            # on a big model/slow FS — it gets the same grace, or every
            # resume of a large job would fire (and with abort, loop)
            wd.pet(-1, grace_s=cfg.watchdog_first_grace_s)
        rollbacks = 0
        preempted = False
        prefetcher = None
        prev_profiled_sync = getattr(tr, "profiled_step_sync", True)
        if cons is not None:
            # lease upkeep off-thread: a step that compiles for a
            # minute must not read as a dead rank to the mesh
            cons.start_heartbeat()
        try:
            start = el.resume()
            self._merge_resumed_skips()
            have_ckpt = el.manager.latest_step() is not None
            # async dispatch: a PROFILED trainer step must not force its
            # own per-step loss sync (hybrid.py profiled_step_sync) —
            # drain() records the honest hybrid/sync_wait span instead
            # (restored in the finally below)
            tr.profiled_step_sync = not cfg.async_dispatch
            if cfg.prefetch_depth > 0:
                from ..distributed.prefetch import BatchPrefetcher

                # fetch rides the SAME retry wrapper; the persisted
                # blocklist is consulted before a cursor is even read
                prefetcher = BatchPrefetcher(
                    lambda c: self._fetch(fetch, c),
                    stage=el._stage_for_prefetch,
                    depth=cfg.prefetch_depth,
                    skip_fn=self._skips.__contains__).start(el.data_cursor)
            self.prefetcher = prefetcher
            losses: Dict[int, float] = {}
            pending: list = []    # (step, cursor, dev_loss, dev_verdict)
            rolled: list = [None]  # (target_step, restored) from a drain
            consecutive_bad = 0
            bad_cursors: list = []
            first = True
            step = start

            def drain(keep: int = 0) -> bool:
                """Materialize the oldest in-flight steps down to
                ``keep``, running the bad-step accounting for each.
                Returns False when a K-streak rollback interrupted the
                drain: every younger in-flight step is discarded (its
                timeline is gone — the restore rewound state, RNG and
                cursor) and ``rolled[0]`` holds where to continue."""
                nonlocal consecutive_bad, bad_cursors, rollbacks
                while len(pending) > keep:
                    s, cur, dev, okdev = pending.pop(0)
                    lossf = el._sync_loss(dev)
                    if guarded:
                        ok = bool(np.asarray(okdev)) if okdev is not None \
                            else tr.last_step_ok
                    else:
                        ok = not (math.isnan(lossf) or math.isinf(lossf))
                    if not ok:
                        reg.counter("resilience/steps_skipped").add(1)
                        consecutive_bad += 1
                        bad_cursors.append(cur)
                        if consecutive_bad >= cfg.bad_step_limit:
                            if wd is not None:
                                # the rollback's checkpoint restore is
                                # as slow as the startup one — same
                                # grace (it also covers the consensus
                                # wait for the other ranks to join)
                                wd.pet(s,
                                       grace_s=cfg.watchdog_first_grace_s)
                            roll_cursors = bad_cursors
                            roll_target = None
                            if cons is not None:
                                # THIS rank's verdict becomes the
                                # mesh's: propose, wait for the ranks
                                # that saw nothing wrong, adopt the
                                # union cursor set + min restore
                                # target (or the abort). The streak
                                # covers steps
                                # [s - consecutive_bad + 1, s]; the
                                # vote's restorable is the newest
                                # commit not younger than its start.
                                verdict = "abort" if (
                                    el.manager.latest_step() is None
                                    and not guarded) else "rollback"
                                dec = self._mesh_agree(
                                    verdict, bad_cursors,
                                    restorable=self._restorable(
                                        s - consecutive_bad + 1))
                                roll_cursors = dec["bad_cursors"]
                                roll_target = dec.get("target")
                            back = self._rollback(roll_cursors, guarded,
                                                  target=roll_target)
                            rollbacks += 1
                            consecutive_bad = 0
                            bad_cursors = []
                            n_younger = len(pending)
                            pending.clear()
                            if prefetcher is not None:
                                prefetcher.invalidate(el.data_cursor)
                            if back >= 0:
                                # replay: forget the rolled-over steps
                                for s2 in [s2 for s2 in losses
                                           if s2 >= back]:
                                    del losses[s2]
                                rolled[0] = (back, True)
                            else:
                                # continue in place (guarded, nothing
                                # committed): re-run this step index
                                # with the re-seeded cursor. The window
                                # only opens once a checkpoint commits,
                                # so younger in-flight steps here mean
                                # every commit VANISHED mid-run — their
                                # already-applied updates cannot be
                                # rewound, and re-running their indices
                                # would double-apply. Fail loudly.
                                if n_younger:
                                    raise RuntimeError(
                                        f"K consecutive bad steps with "
                                        f"no readable committed "
                                        f"checkpoint while {n_younger} "
                                        f"younger async-dispatched "
                                        f"step(s) were in flight "
                                        f"(commits removed mid-run?): "
                                        f"state cannot be rewound")
                                rolled[0] = (s, False)
                            return False
                    else:
                        consecutive_bad = 0
                        bad_cursors = []
                    losses[s] = lossf
                    if on_step is not None:
                        on_step(s, lossf)
                return True

            def resume_after_rollback():
                nonlocal step, first
                back, restored = rolled[0]
                step = back
                if restored:
                    first = True       # restored state may retrace
                rolled[0] = None

            def join_mesh_round() -> bool:
                """A peer opened a ``resil`` round: drain the window
                (our own streak may complete inside — that path joins
                the SAME round as proposer), then join as healthy and
                execute whatever the mesh agreed. Returns False when
                the drain's own rollback already handled everything."""
                nonlocal step, first, rollbacks, consecutive_bad, \
                    bad_cursors
                if not drain(0):
                    return False
                # this rank's own partial streak (may be empty) covers
                # steps [step - consecutive_bad, step - 1]; its vote
                # offers the newest commit at or before that start
                dec = self._mesh_agree(
                    "healthy", bad_cursors,
                    restorable=self._restorable(step - consecutive_bad))
                if dec["verdict"] != "rollback":
                    return True
                if wd is not None:
                    wd.pet(step, grace_s=cfg.watchdog_first_grace_s)
                back = self._rollback(dec["bad_cursors"], guarded,
                                      target=dec.get("target"))
                rollbacks += 1
                consecutive_bad = 0
                bad_cursors = []
                if prefetcher is not None:
                    prefetcher.invalidate(el.data_cursor)
                if back >= 0:
                    for s2 in [s2 for s2 in losses if s2 >= back]:
                        del losses[s2]
                    step = back
                    first = True
                # back < 0: guarded with nothing committed — continue
                # in place; the union cursors are blocklisted, so the
                # next fetch skips them exactly like the proposer's
                return True

            while True:
                if cons is not None and cons.pending("resil"):
                    if not join_mesh_round():
                        resume_after_rollback()
                    continue
                if step >= total_steps:
                    if not drain(0):
                        resume_after_rollback()
                        continue
                    break
                if wd is not None:
                    wd.pet(step, grace_s=cfg.watchdog_first_grace_s
                           if first else 0.0)
                self._advance_past_skips()
                cursor = el.data_cursor
                if prefetcher is not None:
                    batch = prefetcher.get(cursor)
                else:
                    batch = self._fetch(fetch, cursor)
                    if not isinstance(batch, tuple):
                        batch = (batch,)
                if chaos is not None:
                    chaos.maybe_hang(step)
                    if guarded and chaos.poisons(cursor):
                        tr.inject_fault_scale(float("nan"))
                # streamed-snapshot gate LAST before the dispatch (which
                # donates the state an in-flight save may still be
                # copying to host): the fetch/staging above overlaps the
                # snapshot's D2H
                el.manager.wait_snapshot()
                loss = tr.step(*batch)
                el.data_cursor = cursor + 1
                okdev = get_ok() if (guarded and get_ok is not None) \
                    else None
                first = False
                pending.append((step, cursor, loss, okdev))
                done = step + 1
                step = done

                # in-flight window: 0 (materialize now) unless async
                # dispatch is on, a committed checkpoint anchors a
                # potential rollback, AND per-step device verdicts are
                # available (can_defer); sync_interval forces a drain
                window = cfg.max_inflight if (cfg.async_dispatch
                                              and have_ckpt
                                              and can_defer) else 0
                if window and done % cfg.sync_interval == 0:
                    window = 0
                if not drain(keep=window):
                    resume_after_rollback()
                    continue

                if chaos is not None:
                    chaos.maybe_preempt(done - 1)
                if handler.requested:
                    # make the exit resumable: drain everything the
                    # in-flight window holds, then one synchronous
                    # committed save. NEVER mid-streak (even guarded):
                    # a preemption is asymmetric — the uninterrupted
                    # run has no restore point here, so committing one
                    # would shift the K-streak rollback target and
                    # break loss-curve parity. The restart resumes from
                    # the last streak-free checkpoint and
                    # deterministically replays the streak instead.
                    if not drain(0):
                        resume_after_rollback()
                        continue
                    if consecutive_bad == 0:
                        if wd is not None:
                            # a synchronous big-model save is as slow
                            # as a restore — same grace, or abort mode
                            # kills the commit it exists to protect
                            wd.pet(done,
                                   grace_s=cfg.watchdog_first_grace_s)
                        el.save(done, extra=self._extra_meta(),
                                async_=False)
                        have_ckpt = True
                    reg.counter("resilience/preemptions").add(1)
                    # persist the lifetime's telemetry AFTER the commit
                    # (the PR 2 rule: the handler stays async-signal-
                    # trivial; all I/O happens here at the step
                    # boundary, before the resumable exit)
                    from ..profiler import sink as _psink

                    _psink.flush_active("preempt")
                    preempted = True
                    break
                if done % el.save_interval == 0 or done == total_steps:
                    if not drain(0):
                        resume_after_rollback()
                        continue
                    # saveable: a GUARDED trainer's weights are clean
                    # even mid-bad-streak (the update was deselected);
                    # WITHOUT the guard (host-side NaN check only) the
                    # poisoned update already landed, and committing it
                    # would make the NaN weights the rollback/restart
                    # target — an unrecoverable livelock
                    if guarded or consecutive_bad == 0:
                        el.save(done, extra=self._extra_meta())
                        have_ckpt = True
            if wd is not None:     # joining the async save can be slow
                wd.pet(step, grace_s=cfg.watchdog_first_grace_s)
            el.manager.wait()
            if preempted and cfg.raise_on_preempt:
                from .preemption import PreemptedError

                raise PreemptedError(step, handler.signum or 0,
                                     el.manager.directory)
            return RunResult(losses=losses, start_step=start,
                             final_step=step, total_steps=total_steps,
                             preempted=preempted, rollbacks=rollbacks)
        finally:
            tr.profiled_step_sync = prev_profiled_sync
            if cons is not None:
                cons.stop_heartbeat()
            if prefetcher is not None:
                prefetcher.stop()
            if wd is not None:
                wd.stop()
            handler.uninstall()
