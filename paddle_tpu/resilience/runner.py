"""The hardened training loop: bad-step guard + rollback, graceful
preemption, step watchdog, degraded restore, retried data loading.

``ResilientRunner`` wraps a ``HybridPipelineTrainer`` (or anything with
the same ``step``/``device_state``/``load_device_state`` surface)
behind an ``ElasticTrainer`` and runs the loop the ISSUE tentpole
specifies:

  1. **bad-step guard** — the trainer's compiled finite check
     (``guard_bad_steps``) skips the update on a NaN/Inf step; the
     runner counts consecutive bad steps and after
     ``bad_step_limit`` of them ROLLS BACK to the newest readable
     committed checkpoint and re-seeds the data cursor past the
     offending batches (they land in a persisted skip set, so replay —
     and any later restart — never feeds them again).
  2. **graceful preemption** — SIGTERM/SIGINT set a flag; the in-flight
     step finishes, a synchronous committed checkpoint lands, and
     ``run`` returns a RunResult carrying the resumable exit code.
  3. **step watchdog** — a monitor thread that dumps live stacks +
     profiler span state on a hung step and optionally aborts so the
     elastic restart path takes over (resilience/watchdog.py).
  4. **degraded restore** — resume walks back past corrupt newest
     steps (checkpoint.restore_degraded) instead of dying.
  5. data loading rides ``utils.retry`` with exponential backoff.

Every recovery event moves a profiler counter: ``resilience/
steps_skipped``, ``resilience/rollbacks``, ``resilience/
restore_fallbacks``, ``resilience/preemptions``, ``resilience/
data_retries``, ``resilience/watchdog_fires``.

Determinism contract: with a fixed ``ChaosPlan``, a run that is
preempted, corrupted, and restarted produces the SAME per-step losses
as an uninterrupted run (the chaos e2e test asserts this bitwise).

Known limit (ROADMAP): rollback decisions are host-local. On a
multi-host mesh every process computes the same verdict from the same
replicated loss/grads, so they agree in lockstep — but there is no
explicit cross-host agreement protocol yet for faults only one host
sees (a local data-loader giving up, a local watchdog fire).
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

from ..distributed.elastic import ElasticTrainer
from ..profiler.metrics import registry as _registry
from ..utils.retry import retry
from .preemption import PREEMPT_EXIT_CODE, PreemptionHandler
from .watchdog import StepWatchdog

__all__ = ["ResilienceConfig", "ResilientRunner", "RunResult"]


class ResilienceConfig:
    """Knobs of the hardened loop (README "Resilience" documents them).

    bad_step_limit:         consecutive guarded-bad steps before a
                            rollback (K).
    watchdog_timeout_s:     None disables the watchdog.
    watchdog_first_grace_s: extra allowance for a lifetime's first step
                            (jit compile); default 10× the timeout.
    watchdog_jitter:        deadline jitter fraction (fleet de-sync).
    watchdog_abort:         hard-exit on fire (WATCHDOG_EXIT_CODE).
    data_retry_attempts /   retry-with-exponential-backoff policy for
    data_retry_base_delay:  data_fn calls (utils.retry).
    verify_restore:         CRC-verify shards on resume (the walk-back
                            can only SEE silent corruption when on).
    raise_on_preempt:       raise PreemptedError after the preemption
                            checkpoint commits, instead of returning a
                            RunResult with preempted=True (default).
    """

    def __init__(self,
                 bad_step_limit: int = 3,
                 watchdog_timeout_s: Optional[float] = None,
                 watchdog_first_grace_s: Optional[float] = None,
                 watchdog_jitter: float = 0.1,
                 watchdog_abort: bool = False,
                 watchdog_dump_file: Optional[str] = None,
                 watchdog_seed: int = 0,
                 data_retry_attempts: int = 4,
                 data_retry_base_delay: float = 0.05,
                 data_retry_max_delay: float = 5.0,
                 data_retry_jitter: float = 0.0,
                 verify_restore: bool = True,
                 raise_on_preempt: bool = False):
        if bad_step_limit < 1:
            raise ValueError("bad_step_limit must be >= 1")
        self.bad_step_limit = int(bad_step_limit)
        self.watchdog_timeout_s = watchdog_timeout_s
        self.watchdog_first_grace_s = watchdog_first_grace_s if \
            watchdog_first_grace_s is not None else (
                10.0 * watchdog_timeout_s if watchdog_timeout_s else 0.0)
        self.watchdog_jitter = watchdog_jitter
        self.watchdog_abort = watchdog_abort
        self.watchdog_dump_file = watchdog_dump_file
        self.watchdog_seed = watchdog_seed
        self.data_retry_attempts = int(data_retry_attempts)
        self.data_retry_base_delay = float(data_retry_base_delay)
        self.data_retry_max_delay = float(data_retry_max_delay)
        self.data_retry_jitter = float(data_retry_jitter)
        self.verify_restore = bool(verify_restore)
        self.raise_on_preempt = bool(raise_on_preempt)


class RunResult:
    """What a resilient run lifetime produced.

    losses:      {step: loss} for every step this LIFETIME executed and
                 kept (rollback-discarded steps are removed).
    preempted:   True when the run stopped on a preemption request
                 after committing its checkpoint; ``exit_code`` is then
                 the resumable status (75/EX_TEMPFAIL) a worker should
                 exit with so the supervisor reschedules it.
    completed:   reached total_steps.
    """

    def __init__(self, losses: Dict[int, float], start_step: int,
                 final_step: int, total_steps: int, preempted: bool,
                 rollbacks: int):
        self.losses = losses
        self.start_step = start_step
        self.final_step = final_step
        self.total_steps = total_steps
        self.preempted = preempted
        self.rollbacks = rollbacks

    @property
    def completed(self) -> bool:
        return not self.preempted and self.final_step >= self.total_steps

    @property
    def exit_code(self) -> int:
        return PREEMPT_EXIT_CODE if self.preempted else 0

    def loss_list(self):
        """Losses as a dense list ordered by step (steps this lifetime)."""
        return [self.losses[s] for s in sorted(self.losses)]


class ResilientRunner:
    def __init__(self, trainer, ckpt_dir: str, save_interval: int = 100,
                 keep: int = 3, config: Optional[ResilienceConfig] = None,
                 chaos=None):
        self.config = config or ResilienceConfig()
        self.chaos = chaos
        self.elastic = ElasticTrainer(
            trainer, ckpt_dir, save_interval=save_interval, keep=keep,
            degraded_restore=True,
            verify_restore=self.config.verify_restore)
        self.trainer = trainer
        self.preemption = PreemptionHandler()
        # cursors whose batches poisoned a rollback — never fed again;
        # persisted in every checkpoint's meta so restarts keep them
        self._skips: set = set()

    # -- helpers -----------------------------------------------------------
    def _extra_meta(self) -> dict:
        return {"skipped_cursors": sorted(self._skips)}

    def _merge_resumed_skips(self) -> None:
        self._skips.update(
            int(c) for c in self.elastic.last_meta.get(
                "skipped_cursors", []))

    def _advance_past_skips(self) -> None:
        el = self.elastic
        while el.data_cursor in self._skips:
            el.data_cursor += 1

    def _fetch(self, data_fn, cursor: int):
        cfg = self.config
        reg = _registry()

        def _note(i, e, d):
            reg.counter("resilience/data_retries").add(1)

        return retry(lambda: data_fn(cursor),
                     attempts=cfg.data_retry_attempts,
                     base_delay=cfg.data_retry_base_delay,
                     max_delay=cfg.data_retry_max_delay,
                     jitter=cfg.data_retry_jitter,
                     seed=cursor,          # deterministic per batch
                     on_retry=_note)

    def _rollback(self, bad_cursors, guarded: bool) -> int:
        """K consecutive bad steps: restore the newest readable
        committed checkpoint and blocklist the poisoned cursors.
        Returns the step to continue from. With no committed checkpoint
        yet, a GUARDED trainer just continues past the bad batches (the
        compiled guard kept the weights clean; the cursors stay
        blocklisted for any future replay) — an UNGUARDED one has
        already taken the poisoned updates with nothing to restore, so
        the only honest move is to fail loudly."""
        el = self.elastic
        _registry().counter("resilience/rollbacks").add(1)
        self._skips.update(bad_cursors)
        el.manager.wait()              # never restore under an async save
        if el.manager.latest_step() is None:
            if not guarded:
                raise RuntimeError(
                    f"{len(bad_cursors)} consecutive non-finite steps "
                    "on a trainer WITHOUT guard_bad_steps and no "
                    "committed checkpoint to roll back to: the weights "
                    "are poisoned and unrecoverable. Enable "
                    "guard_bad_steps or checkpoint before the first "
                    "fault window.")
            return -1                  # continue in place
        step = el.resume()
        self._merge_resumed_skips()
        return step

    # -- the loop ----------------------------------------------------------
    def run(self, data_fn, total_steps: int, on_step=None) -> RunResult:
        cfg = self.config
        el = self.elastic
        tr = self.trainer
        chaos = self.chaos
        reg = _registry()
        guarded = bool(getattr(tr, "guard_bad_steps", False))
        fetch = chaos.wrap_data_fn(data_fn) if chaos is not None \
            else data_fn

        handler = self.preemption
        handler.clear()
        handler.install()
        wd = None
        if cfg.watchdog_timeout_s:
            wd = StepWatchdog(cfg.watchdog_timeout_s,
                              jitter_frac=cfg.watchdog_jitter,
                              abort=cfg.watchdog_abort,
                              dump_file=cfg.watchdog_dump_file,
                              seed=cfg.watchdog_seed).start()
            # the checkpoint restore below is as slow as a first compile
            # on a big model/slow FS — it gets the same grace, or every
            # resume of a large job would fire (and with abort, loop)
            wd.pet(-1, grace_s=cfg.watchdog_first_grace_s)
        rollbacks = 0
        preempted = False
        try:
            start = el.resume()
            self._merge_resumed_skips()
            losses: Dict[int, float] = {}
            consecutive_bad = 0
            bad_cursors: list = []
            first = True
            step = start
            while step < total_steps:
                if wd is not None:
                    wd.pet(step, grace_s=cfg.watchdog_first_grace_s
                           if first else 0.0)
                self._advance_past_skips()
                cursor = el.data_cursor
                batch = self._fetch(fetch, cursor)
                if not isinstance(batch, tuple):
                    batch = (batch,)
                if chaos is not None:
                    chaos.maybe_hang(step)
                    if guarded and chaos.poisons(cursor):
                        tr.inject_fault_scale(float("nan"))
                loss = tr.step(*batch)
                el.data_cursor = cursor + 1
                lossf = float(np.asarray(loss))
                first = False
                ok = tr.last_step_ok if guarded else \
                    not (math.isnan(lossf) or math.isinf(lossf))
                if not ok:
                    reg.counter("resilience/steps_skipped").add(1)
                    consecutive_bad += 1
                    bad_cursors.append(cursor)
                    if consecutive_bad >= cfg.bad_step_limit:
                        if wd is not None:
                            # the rollback's checkpoint restore is as
                            # slow as the startup one — same grace
                            wd.pet(step,
                                   grace_s=cfg.watchdog_first_grace_s)
                        back = self._rollback(bad_cursors, guarded)
                        rollbacks += 1
                        consecutive_bad = 0
                        bad_cursors = []
                        if back >= 0:
                            # replay: forget the steps being rolled over
                            for s in [s for s in losses if s >= back]:
                                del losses[s]
                            step = back
                            first = True   # restored state may retrace
                        continue
                else:
                    consecutive_bad = 0
                    bad_cursors = []
                losses[step] = lossf
                done = step + 1
                # saveable: a GUARDED trainer's weights are clean even
                # mid-bad-streak (the update was deselected); WITHOUT
                # the guard (host-side NaN check only) the poisoned
                # update already landed, and committing it would make
                # the NaN weights the rollback/restart target — an
                # unrecoverable livelock
                saveable = guarded or consecutive_bad == 0
                if chaos is not None:
                    chaos.maybe_preempt(step)
                if handler.requested:
                    # the in-flight step finished above; now make the
                    # exit resumable: one synchronous committed save.
                    # NEVER mid-streak (even guarded): a preemption is
                    # asymmetric — the uninterrupted run has no restore
                    # point here, so committing one would shift the
                    # K-streak rollback target and break loss-curve
                    # parity. The restart resumes from the last
                    # streak-free checkpoint and deterministically
                    # replays the streak instead.
                    if consecutive_bad == 0:
                        if wd is not None:
                            # a synchronous big-model save is as slow
                            # as a restore — same grace, or abort mode
                            # kills the commit it exists to protect
                            wd.pet(step,
                                   grace_s=cfg.watchdog_first_grace_s)
                        el.save(done, extra=self._extra_meta(),
                                async_=False)
                    reg.counter("resilience/preemptions").add(1)
                    preempted = True
                    if on_step is not None:
                        on_step(step, lossf)
                    step = done
                    break
                if saveable and (done % el.save_interval == 0
                                 or done == total_steps):
                    el.save(done, extra=self._extra_meta())
                if on_step is not None:
                    on_step(step, lossf)
                step = done
            if wd is not None:     # joining the async save can be slow
                wd.pet(step, grace_s=cfg.watchdog_first_grace_s)
            el.manager.wait()
            if preempted and cfg.raise_on_preempt:
                from .preemption import PreemptedError

                raise PreemptedError(step, handler.signum or 0,
                                     el.manager.directory)
            return RunResult(losses=losses, start_step=start,
                             final_step=step, total_steps=total_steps,
                             preempted=preempted, rollbacks=rollbacks)
        finally:
            if wd is not None:
                wd.stop()
            handler.uninstall()
