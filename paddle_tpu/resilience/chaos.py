"""Deterministic fault injection for the resilience test suite.

Every fault the runtime claims to survive is injected here, on a fixed
schedule keyed by data cursor / step number — NOT randomly — so a run
with a given ``ChaosPlan`` is exactly reproducible: the chaos e2e test
compares a preempted-corrupted-restarted run bitwise against an
uninterrupted run with the SAME plan.

Fault classes (ISSUE tentpole (5)):
  - NaN gradients: ``nan_cursors`` — the runner calls the trainer's
    ``inject_fault_scale(nan)`` hook for those batches, poisoning loss
    and gradients inside the compiled step (guard_bad_steps catches it).
  - data-loader exceptions: ``flaky_cursors`` — the wrapped data_fn
    raises ``ChaosDataError`` a configured number of times per cursor
    before succeeding (exercises retry-with-backoff).
  - artificial step hangs: ``hang_steps`` — ``maybe_hang(step)`` sleeps
    past the watchdog timeout.
  - self-preemption: ``preempt_after_step`` — after that step completes
    the plan raises SIGTERM in-process (deterministic stand-in for the
    fleet scheduler's signal).
  - checkpoint corruption: module-level file surgeons below (truncated
    shard, flipped bytes with valid length, deleted COMMIT, deleted
    shard, kill-mid-save simulation).
"""
from __future__ import annotations

import os
import signal
import time
from typing import Dict, Iterable, Optional

__all__ = ["ChaosPlan", "ChaosDataError", "truncate_shard",
           "flip_shard_byte", "delete_commit", "delete_shard",
           "simulate_kill_mid_save", "abandon_async_save",
           "newest_committed_step"]


class ChaosDataError(RuntimeError):
    """The injected transient data-loader failure."""


# ---------------------------------------------------------------------------
# checkpoint-directory surgeons (operate on distributed/checkpoint.py layout)
# ---------------------------------------------------------------------------


def _step_dir(ckpt_dir: str, step: Optional[int]) -> str:
    from ..distributed import checkpoint as dck

    if step is None:
        step = newest_committed_step(ckpt_dir)
    return os.path.join(ckpt_dir, dck._STEP_FMT.format(step))


def newest_committed_step(ckpt_dir: str) -> int:
    from ..distributed import checkpoint as dck

    step = dck.latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint in {ckpt_dir}")
    return step


def _shard_path(ckpt_dir: str, step: Optional[int], proc: int) -> str:
    return os.path.join(_step_dir(ckpt_dir, step), f"shard_p{proc}.bin")


def truncate_shard(ckpt_dir: str, step: Optional[int] = None,
                   keep_bytes: int = 16, proc: int = 0) -> str:
    """Cut a shard file short (a crash mid-write after COMMIT was
    already durable on another host, or a filesystem losing a tail)."""
    p = _shard_path(ckpt_dir, step, proc)
    with open(p, "r+b") as f:
        f.truncate(keep_bytes)
    return p


def flip_shard_byte(ckpt_dir: str, step: Optional[int] = None,
                    offset: int = 10, proc: int = 0) -> str:
    """Silent bit rot: XOR one byte, length unchanged — only a CRC
    verify can see this."""
    p = _shard_path(ckpt_dir, step, proc)
    with open(p, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return p


def delete_commit(ckpt_dir: str, step: Optional[int] = None) -> str:
    """Remove the COMMIT marker: the step must stop counting as
    committed (latest_step walks past it)."""
    d = _step_dir(ckpt_dir, step)
    p = os.path.join(d, "COMMIT")
    os.unlink(p)
    return d


def delete_shard(ckpt_dir: str, step: Optional[int] = None,
                 proc: int = 0) -> str:
    """Lose a whole shard file (dead disk / evicted cache object)."""
    p = _shard_path(ckpt_dir, step, proc)
    os.unlink(p)
    return p


def simulate_kill_mid_save(ckpt_dir: str, step: int) -> str:
    """Shard bytes present, COMMIT absent — the exact on-disk state a
    SIGKILL between fsync and commit leaves behind."""
    from ..distributed import checkpoint as dck

    d = os.path.join(ckpt_dir, dck._STEP_FMT.format(step))
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "shard_p0.bin"), "wb") as f:
        f.write(b"\x00" * 64)
    # no manifest, no COMMIT
    return d


def abandon_async_save(handle) -> str:
    """Kill-mid-snapshot for a REAL streamed save (checkpoint.save
    snapshot_async): join the writer thread — the most adversarial
    surviving state, every shard byte + manifest durable on disk — but
    never run ``wait()``, so COMMIT is never written. Deterministic
    stand-in for a SIGKILL landing between the last fsync and the
    commit marker; ``latest_step`` must keep resolving to the previous
    committed step. Returns the uncommitted step directory."""
    handle._thread.join()
    return handle.directory


# ---------------------------------------------------------------------------
# the in-loop plan
# ---------------------------------------------------------------------------


class ChaosPlan:
    """Deterministic fault schedule consumed by the resilient runner.

    nan_cursors:        data cursors whose batch poisons the gradients.
    flaky_cursors:      {cursor: n_failures} — data_fn raises
                        ChaosDataError that many times for the cursor
                        before succeeding.
    hang_steps:         {step: seconds} — sleep after the step's batch
                        is fetched (watchdog bait).
    preempt_after_step: send SIGTERM to this process after the step
                        completes (None: never).
    """

    def __init__(self,
                 nan_cursors: Iterable[int] = (),
                 flaky_cursors: Optional[Dict[int, int]] = None,
                 hang_steps: Optional[Dict[int, float]] = None,
                 preempt_after_step: Optional[int] = None):
        self.nan_cursors = frozenset(int(c) for c in nan_cursors)
        self.flaky_cursors = dict(flaky_cursors or {})
        self.hang_steps = {int(k): float(v)
                           for k, v in (hang_steps or {}).items()}
        self.preempt_after_step = preempt_after_step
        self._remaining_failures = dict(self.flaky_cursors)

    # -- hooks the runner calls -------------------------------------------
    def poisons(self, cursor: int) -> bool:
        return cursor in self.nan_cursors

    def wrap_data_fn(self, data_fn):
        """data_fn(cursor) that raises ChaosDataError the configured
        number of times per flaky cursor, then delegates."""
        def chaotic(cursor):
            left = self._remaining_failures.get(cursor, 0)
            if left > 0:
                self._remaining_failures[cursor] = left - 1
                raise ChaosDataError(
                    f"injected data-loader failure for cursor {cursor} "
                    f"({left - 1} more to come)")
            return data_fn(cursor)

        return chaotic

    def maybe_hang(self, step: int) -> None:
        s = self.hang_steps.get(step)
        if s:
            time.sleep(s)

    def maybe_preempt(self, step: int) -> None:
        if self.preempt_after_step is not None \
                and step == self.preempt_after_step:
            os.kill(os.getpid(), signal.SIGTERM)
