"""Graceful-preemption signal handling.

TPU fleets preempt VMs with a SIGTERM and a grace window; the reference
framework's launcher reacts by tearing the whole job down
(launch_utils.py terminate_local_procs). Here preemption is a normal,
resumable event: the handler only RECORDS the request, the training
loop (resilience/runner.py) finishes the in-flight step, forces a
synchronous committed checkpoint, and exits with a resumable status —
the restarted process continues the exact loss curve.

The handler is deliberately async-signal-trivial: it flips a flag and
remembers the signal number. No I/O, no locks, no collectives in the
handler itself (a checkpoint collective issued from a signal frame
could interleave with training collectives and deadlock XLA — the same
rule SaveHandle.wait documents for background threads).

Async-step-pipeline interplay (ISSUE 3): with deferred loss sync the
loop may hold a window of dispatched-but-unmaterialized steps when the
flag is seen. The preemption flush FIRST drains that window (running
the normal bad-step accounting for each in-flight step — a preemption
must not skip a rollback the synchronous loop would have taken), THEN
takes the synchronous committed save; an in-flight streamed checkpoint
snapshot is joined by that save's own manager.wait(). So the
exit-checkpoint invariant is unchanged: the committed state is exactly
the state after the last materialized clean step.
"""
from __future__ import annotations

import signal
import threading
from typing import Iterable, Optional

__all__ = ["PreemptionHandler", "PreemptedError", "PREEMPT_EXIT_CODE"]

# EX_TEMPFAIL: the conventional "transient failure, retry me" status —
# a supervisor (k8s restartPolicy, the elastic launcher) distinguishes
# it from a real crash and reschedules instead of alerting.
PREEMPT_EXIT_CODE = 75


class PreemptedError(RuntimeError):
    """Raised by the resilient runner after the preemption checkpoint
    is committed — opt-in via ``ResilienceConfig(raise_on_preempt=
    True)``; the default path returns ``RunResult(preempted=True)``
    instead. Carries everything a supervisor needs to resume."""

    def __init__(self, step: int, signum: int, ckpt_dir: Optional[str]):
        super().__init__(
            f"preempted by signal {signum} at step {step}; committed "
            f"checkpoint in {ckpt_dir!r} — exit {PREEMPT_EXIT_CODE} and "
            f"restart to resume")
        self.step = step
        self.signum = signum
        self.ckpt_dir = ckpt_dir
        self.exit_code = PREEMPT_EXIT_CODE


class PreemptionHandler:
    """Install SIGTERM/SIGINT handlers that set a flag; the training
    loop polls ``requested`` at step boundaries.

    Context-manager use restores the previous handlers on exit. Only the
    main thread may install signal handlers (CPython rule); installing
    from another thread degrades to a no-op so library code can use the
    handler unconditionally. ``request()`` triggers the same path
    programmatically (chaos harness, cluster-notice pollers).
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self.signum: Optional[int] = None
        self._prev: dict = {}
        self._installed = False

    # -- flag --------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self, signum: int = signal.SIGTERM) -> None:
        self.signum = signum
        self._event.set()

    def clear(self) -> None:
        self._event.clear()
        self.signum = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    # -- installation ------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        self.request(signum)

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False
