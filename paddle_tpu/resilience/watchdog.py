"""Step watchdog: a monitor thread that fires on hung training steps.

A hung step on a real fleet (a wedged collective, a deadlocked host
callback, a dead data source) looks exactly like a slow step from the
outside — nothing raises, the job just stops. The watchdog turns that
silence into a diagnosis: when no ``pet()`` arrives within the timeout
it dumps every thread's live Python stack plus the profiler's open span
stacks and per-scope summary (the spans say WHICH phase wedged), writes
a flight-recorder JSON (recent events + metric deltas + open spans —
``profiler.events.dump_flight``), flushes the active metrics sink with
reason ``"watchdog"``, bumps ``resilience/watchdog_fires``, and
optionally aborts the process so the elastic restart path takes over.

The effective deadline is jittered (multiplier in
``[1, 1+jitter_frac]``, seeded RNG): a fleet-wide stall must not make
every host dump and abort in the same instant, or the shared filesystem
eats ten thousand simultaneous stack dumps. ``jitter_frac=0`` gives the
deterministic deadline tests need.
"""
from __future__ import annotations

import os
import random
import sys
import threading
import time
import traceback
from typing import Callable, Optional

__all__ = ["StepWatchdog", "WATCHDOG_EXIT_CODE"]

# EX_IOERR-adjacent but distinct from the preemption code: a supervisor
# can tell "hung and self-aborted" from "preempted, resumable".
WATCHDOG_EXIT_CODE = 74


def dump_stacks(out=None) -> str:
    """All threads' Python stacks + profiler live-span/scope state, as
    one string (also written to ``out``, default stderr)."""
    from ..profiler import trace as _ptrace

    lines = ["=== resilience.watchdog: hung-step dump ==="]
    live = _ptrace.live_spans()
    if live:
        lines.append("open profiler spans (thread -> scope stack):")
        for tid, stack in sorted(live.items()):
            lines.append(f"  thread {tid}: {' > '.join(stack)}")
    summ = _ptrace.scope_summary()
    if summ:
        lines.append("profiler scope summary:")
        for name, s in sorted(summ.items()):
            lines.append(
                f"  {name}: n={s['count']} mean={s['mean_ms']}ms "
                f"max={s['max_ms']}ms")
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        lines.append("".join(traceback.format_stack(frame)).rstrip())
    text = "\n".join(lines) + "\n"
    f = out if out is not None else sys.stderr
    try:
        f.write(text)
        f.flush()
    except (OSError, ValueError):
        pass                      # a dump must never take the job down
    return text


class StepWatchdog:
    """``start()`` the monitor, ``pet(step)`` after every completed
    step, ``stop()`` when the loop exits (context manager does both).

    timeout_s:    max wall time between pets before the watchdog fires.
    jitter_frac:  deadline multiplier drawn uniformly from
                  [1, 1+jitter_frac] per pet (seeded — deterministic).
    on_fire:      callable(step, elapsed_s, dump_text) observing the
                  fire (tests, alerting hooks).
    abort:        after dumping, hard-exit with WATCHDOG_EXIT_CODE so a
                  supervisor restarts the job (os._exit: a wedged XLA
                  runtime cannot be trusted to run atexit handlers).
    dump_file:    optional path; the dump is appended there as well as
                  to stderr (shared-FS flight recorder).
    """

    def __init__(self, timeout_s: float, jitter_frac: float = 0.1,
                 abort: bool = False,
                 on_fire: Optional[Callable] = None,
                 dump_file: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 seed: int = 0):
        if timeout_s <= 0:
            raise ValueError("watchdog timeout_s must be > 0")
        self.timeout_s = float(timeout_s)
        self.jitter_frac = max(0.0, float(jitter_frac))
        self.abort = bool(abort)
        self.on_fire = on_fire
        self.dump_file = dump_file
        self.poll_s = poll_s if poll_s is not None \
            else min(0.25, self.timeout_s / 4)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._deadline = 0.0
        self._last_pet_t = 0.0
        self._gen = 0               # pet generation: one fire per gen
        self._step = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False
        #: where the last fire's flight-recorder JSON landed (None when
        #: neither dump_file nor an active sink gave it a home, the
        #: file write failed, or persistence timed out on wedged I/O)
        self.flight_path: Optional[str] = None

    def _new_deadline(self) -> float:
        mult = 1.0 + self._rng.uniform(0.0, self.jitter_frac) \
            if self.jitter_frac else 1.0
        return time.monotonic() + self.timeout_s * mult

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        with self._lock:
            self._deadline = self._new_deadline()
            self._last_pet_t = time.monotonic()
        self._thread = threading.Thread(
            target=self._monitor, name="resilience-watchdog", daemon=True)
        self._thread.start()
        return self

    def pet(self, step: int = -1, grace_s: float = 0.0) -> None:
        """The step heartbeat: call after every completed step.
        ``grace_s`` extends THIS deadline only — the runner grants it to
        the first step of a lifetime, whose jit compile legitimately
        dwarfs the steady-state timeout."""
        with self._lock:
            self._step = step
            self._deadline = self._new_deadline() + max(0.0, grace_s)
            self._last_pet_t = time.monotonic()
            self._gen += 1

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- monitor -----------------------------------------------------------
    def _monitor(self) -> None:
        fired_gen = None
        while not self._stop.wait(self.poll_s):
            with self._lock:
                overdue = time.monotonic() > self._deadline
                step = self._step
                gen = self._gen
            if overdue and gen != fired_gen:
                # one fire per pet generation: a continuing hang is not
                # re-dumped every poll, but the monitor SURVIVES the
                # fire — the next pet re-arms it for later hangs
                fired_gen = gen
                self._fire(step)

    def _fire(self, step: int) -> None:
        from ..profiler import events as _pevents
        from ..profiler import sink as _psink
        from ..profiler.metrics import registry as _registry

        self.fired = True
        _registry().counter("resilience/watchdog_fires").add(1)
        elapsed = time.monotonic() - self._last_pet_t
        text = dump_stacks()
        _pevents.emit("watchdog_fire", step=step,
                      elapsed_s=round(elapsed, 3))

        # post-mortem persistence: the stack dump, the flight-recorder
        # JSON (recent events + metric deltas + open spans, written
        # next to the stack dump or into the active sink's directory),
        # and a sink flush so metrics.jsonl carries a final "watchdog"
        # line. With abort on, the os._exit below skips atexit BY
        # DESIGN, so this is the last chance anything persists — but
        # the hang being diagnosed may BE a wedged filesystem, so ALL
        # of this file I/O runs on a bounded daemon thread: expired,
        # the abort proceeds without the artifact rather than never.
        holder = {}

        def _persist() -> None:
            if self.dump_file:
                try:
                    with open(self.dump_file, "a") as f:
                        f.write(text)
                except OSError:
                    pass
            holder["flight"] = _pevents.dump_flight(
                "watchdog", path=(self.dump_file + ".flight.json")
                if self.dump_file else None)
            # bounded too: the sink's writer thread may be wedged in
            # hung I/O while HOLDING the flush lock
            _psink.flush_active("watchdog", timeout=5.0)

        pt = threading.Thread(target=_persist, name="watchdog-persist",
                              daemon=True)
        pt.start()
        pt.join(timeout=10.0)
        self.flight_path = holder.get("flight")
        if self.on_fire is not None:
            try:
                self.on_fire(step, elapsed, text)
            except Exception:
                traceback.print_exc()
        if self.abort:
            # the hung step may hold the GIL only intermittently and the
            # XLA runtime may be wedged — os._exit is the only exit that
            # cannot itself hang. The elastic restart resumes from the
            # last committed checkpoint.
            os._exit(WATCHDOG_EXIT_CODE)
