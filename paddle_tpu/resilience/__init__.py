"""paddle_tpu.resilience — the fault-tolerant training runtime.

A place the framework SURPASSES the reference (SURVEY §5): the
reference launcher only tears jobs down on failure; here preemptions,
poisoned batches, hung steps, flaky data sources, and corrupted
checkpoints are all survivable, deterministically tested events.

Pieces (each importable alone):

  - ``ResilientRunner`` / ``ResilienceConfig`` (runner.py): the
    hardened loop — bad-step guard + K-consecutive rollback with
    cursor re-seeding, graceful preemption checkpointing, watchdog,
    degraded restore, retried data loading.
  - ``StepWatchdog`` (watchdog.py): hung-step monitor; dumps live
    thread stacks + profiler span state, optionally aborts.
  - ``PreemptionHandler`` / ``PreemptedError`` (preemption.py):
    SIGTERM/SIGINT → flag → finish step → committed checkpoint →
    resumable exit status.
  - ``chaos`` (chaos.py): the deterministic fault-injection harness
    the test suite drives (NaN grads, truncated/corrupt/uncommitted
    shards, data-loader exceptions, artificial hangs, self-preemption).

Recovery events are profiler counters: ``resilience/steps_skipped``,
``resilience/rollbacks``, ``resilience/restore_fallbacks``,
``resilience/preemptions``, ``resilience/data_retries``,
``resilience/watchdog_fires`` (paddle_tpu.profiler registry).

Quick use::

    tr = HybridPipelineTrainer(model, opt, strategy, mesh,
                               guard_bad_steps=True)
    runner = ResilientRunner(tr, ckpt_dir, save_interval=100,
                             config=ResilienceConfig(
                                 bad_step_limit=3,
                                 watchdog_timeout_s=600))
    result = runner.run(data_fn, total_steps)   # data_fn(cursor)
    if result.preempted:
        sys.exit(result.exit_code)              # supervisor restarts
"""
from __future__ import annotations

from . import chaos  # noqa: F401
from .preemption import (PREEMPT_EXIT_CODE, PreemptedError,  # noqa: F401
                         PreemptionHandler)
from .runner import ResilienceConfig, ResilientRunner, RunResult  # noqa: F401
from .watchdog import WATCHDOG_EXIT_CODE, StepWatchdog  # noqa: F401

__all__ = [
    "ResilienceConfig", "ResilientRunner", "RunResult",
    "PreemptionHandler", "PreemptedError", "PREEMPT_EXIT_CODE",
    "StepWatchdog", "WATCHDOG_EXIT_CODE",
    "chaos",
]
