"""Eager tape autograd engine.

TPU-native analogue of the reference imperative engine:
  - op tracing hook   : Tracer::TraceOp        (reference: paddle/fluid/imperative/tracer.cc:132)
  - reverse engine    : BasicEngine::Execute   (reference: imperative/basic_engine.cc:39,265)
  - grad accumulation : GradientAccumulator    (reference: imperative/gradient_accumulator.cc)

Design difference (TPU-first): instead of a registry of hand-written grad
kernels plus a C++ tape, every eager op is executed through ``jax.vjp`` — the
forward runs once (same work as a plain call) and JAX's own VJP rule provides
the exact backward, so the full ~400-op library gets correct gradients with no
per-op backward code. The tape stores the vjp closures; ``backward`` walks
nodes in reverse creation order (a valid topological order for a tape),
mirroring the ready-queue walk of basic_engine.cc:221.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

_state = threading.local()


def _tls():
    if not hasattr(_state, "grad_enabled"):
        _state.grad_enabled = True
    return _state


def is_grad_enabled() -> bool:
    return _tls().grad_enabled


def set_grad_enabled(mode: bool):
    _tls().grad_enabled = bool(mode)


class no_grad:
    """Context manager / decorator disabling tape recording
    (reference: paddle.no_grad, dygraph/base.py)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self


_node_counter = itertools.count()


class Node:
    """One recorded op on the tape (reference: imperative OpBase / GradOpNode)."""

    __slots__ = ("id", "vjp_fn", "parents", "out_specs", "pending", "name",
                 "__weakref__")

    def __init__(self, vjp_fn, parents, out_specs, name=""):
        self.id = next(_node_counter)
        self.vjp_fn = vjp_fn
        self.parents = parents          # list[Tensor] — differentiable inputs
        self.out_specs = out_specs      # list[(shape, dtype)] per output
        self.pending: Dict[int, Any] = {}  # output index -> accumulated cotangent
        self.name = name


def _is_tensor(x) -> bool:
    from ..framework.tensor import Tensor

    return isinstance(x, Tensor)


def _float0_zeros(shape, dtype):
    if np.issubdtype(np.dtype(dtype), np.inexact):
        return jax.numpy.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def apply(fn, *args, n_diff: Optional[int] = None, differentiable: bool = True,
          name: str = "", **kwargs):
    """Execute ``fn`` eagerly, recording a tape node when needed.

    ``fn`` is a pure jax function. Tensor-typed args are unwrapped to their
    jax values; everything else is passed through untouched (static). Returns
    Tensor(s) mirroring fn's output structure.
    """
    from ..framework.tensor import Tensor
    from ..core import flags

    vals = [a._value if _is_tensor(a) else a for a in args]

    # trace-time autocast (reference: tracer.cc:159, amp_auto_cast.cc)
    from ..amp import _amp_state, amp_cast_inputs

    if _amp_state().enabled:
        op_name = name or getattr(fn, "__name__", "op")
        tensor_idx = [i for i, a in enumerate(args) if _is_tensor(a)]
        casted = amp_cast_inputs(op_name, [vals[i] for i in tensor_idx])
        for i, v in zip(tensor_idx, casted):
            vals[i] = v

    diff_idx: List[int] = []
    if differentiable and is_grad_enabled():
        for i, a in enumerate(args):
            if (_is_tensor(a) and not a.stop_gradient
                    and jax.numpy.issubdtype(jax.numpy.asarray(a._value).dtype,
                                             jax.numpy.inexact)):
                diff_idx.append(i)

    # Inside an outer jax transform (jit/grad/linearize — e.g. the hybrid
    # trainer tracing the Layer graph, hybrid_gpt.py), the outer AD owns
    # differentiation: recording a nested jax.vjp here is redundant work and
    # breaks custom_vjp ops (the outer JVP trace would differentiate through
    # the custom fwd's pallas_call). Run the op plainly and let the outer
    # trace see it.
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        out_vals = fn(*vals, **kwargs)
        outs = _wrap_outputs(out_vals, node=None, name=name)
        if isinstance(outs, (tuple, list)):
            for o in outs:
                o.stop_gradient = not diff_idx
        else:
            outs.stop_gradient = not diff_idx
        return outs

    if not diff_idx:
        out_vals = fn(*vals, **kwargs)
        return _wrap_outputs(out_vals, node=None, name=name)

    diff_vals = [vals[i] for i in diff_idx]

    def g(*dvals):
        full = list(vals)
        for i, v in zip(diff_idx, dvals):
            full[i] = v
        return fn(*full, **kwargs)

    out_vals, vjp_fn = jax.vjp(g, *diff_vals)

    multi = isinstance(out_vals, (tuple, list))
    outs_list = list(out_vals) if multi else [out_vals]
    specs = [(np.shape(o), np.result_type(o) if not hasattr(o, "dtype")
              else o.dtype) for o in outs_list]
    node = Node(vjp_fn, [args[i] for i in diff_idx], specs, name or
                getattr(fn, "__name__", "op"))

    outs = _wrap_outputs(out_vals, node=node, name=name)

    if flags.get_flags("check_nan_inf"):
        _check_nan_inf(outs_list, node.name)
    if flags.get_flags("benchmark"):
        for o in outs_list:
            if hasattr(o, "block_until_ready"):
                o.block_until_ready()
    return outs


def _check_nan_inf(out_vals, op_name):
    """FLAGS_check_nan_inf eager scan
    (reference: framework/details/nan_inf_utils_detail.cc:293)."""
    for o in out_vals:
        arr = np.asarray(o)
        if np.issubdtype(arr.dtype, np.inexact) and not np.all(np.isfinite(arr)):
            raise FloatingPointError(
                f"Operator {op_name} output contains NaN/Inf.")


def _wrap_outputs(out_vals, node, name=""):
    from ..framework.tensor import Tensor

    if isinstance(out_vals, (tuple, list)):
        outs = []
        for i, v in enumerate(out_vals):
            t = Tensor(v, stop_gradient=(node is None))
            if node is not None:
                t._node, t._out_idx = node, i
            outs.append(t)
        return type(out_vals)(outs)
    t = Tensor(out_vals, stop_gradient=(node is None))
    if node is not None:
        t._node, t._out_idx = node, 0
    return t


def backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
             retain_graph: bool = False, taps: Optional[Dict[int, Any]] = None,
             sink_only: bool = False):
    """Run reverse accumulation from ``tensors``
    (reference: BasicEngine::Init/Execute, basic_engine.cc:39,265).

    Accumulates into leaf ``Tensor.grad``. When ``taps`` is given (a dict
    keyed by ``id(tensor)`` with value None), cotangents arriving at those
    tensors are ALSO recorded into the dict; with ``sink_only`` leaf ``.grad``
    is left untouched (partial-grad mode, reference partial_grad_engine.cc).
    """
    tensors = [tensors] if _is_tensor(tensors) else list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = [grad_tensors] if _is_tensor(grad_tensors) else list(grad_tensors)

    heap: List[tuple] = []       # max-heap on node id via negation
    in_heap: Dict[int, Node] = {}

    def seed(t, g):
        if g is None:
            if np.prod(t.shape, dtype=np.int64) != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs")
            g = jax.numpy.ones(t._value.shape, t._value.dtype)
        else:
            g = g._value if _is_tensor(g) else jax.numpy.asarray(g)
        _accumulate(t, g, heap, in_heap, taps, sink_only)

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    while heap:
        _, nid = heapq.heappop(heap)
        node = in_heap.pop(nid)
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "set retain_graph=True if needed.")
        cotangents = []
        for i, (shape, dtype) in enumerate(node.out_specs):
            cotangents.append(node.pending.get(i) if i in node.pending
                              else _float0_zeros(shape, dtype))
        node.pending.clear()
        arg = tuple(cotangents) if len(cotangents) > 1 else cotangents[0]
        in_grads = node.vjp_fn(arg)
        if not retain_graph:
            node.vjp_fn = None
        for parent, g in zip(node.parents, in_grads):
            _accumulate(parent, g, heap, in_heap, taps, sink_only)


def _accumulate(t, g, heap, in_heap, taps=None, sink_only=False):
    """Route cotangent g to tensor t: into its producing node's pending slot,
    into leaf .grad, and/or into the taps sink
    (reference: gradient_accumulator.cc)."""
    from ..framework.tensor import Tensor

    if taps is not None and id(t) in taps:
        prev = taps[id(t)]
        taps[id(t)] = g if prev is None else prev + g

    node = getattr(t, "_node", None)
    if node is not None:
        idx = t._out_idx
        if idx in node.pending:
            node.pending[idx] = node.pending[idx] + g
        else:
            node.pending[idx] = g
        if node.id not in in_heap:
            in_heap[node.id] = node
            heapq.heappush(heap, (-node.id, node.id))
    else:
        if t.stop_gradient or sink_only:
            return
        if t.grad is None:
            t.grad = Tensor(g, stop_gradient=True)
        else:
            t.grad._value = t.grad._value + g


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad equivalent (reference: imperative/partial_grad_engine.cc).

    Computes grads of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``.
    ``create_graph`` (double grad) is served by the functional API
    (paddle_tpu.incubate.autograd) — the eager tape records first-order only.
    """
    from ..framework.tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True in eager mode is not supported; use "
            "paddle_tpu.incubate.autograd (jax.grad composition) for "
            "higher-order gradients.")
    from ..framework.tensor import Tensor

    outputs = [outputs] if _is_tensor(outputs) else list(outputs)
    inputs = [inputs] if _is_tensor(inputs) else list(inputs)

    taps = {id(t): None for t in inputs}
    saved = [(t, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    try:
        backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
                 taps=taps, sink_only=True)
    finally:
        for t, sg in saved:
            t.stop_gradient = sg
    results = []
    for t in inputs:
        g = taps[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears unused; pass "
                "allow_unused=True to return None for it.")
        results.append(None if g is None else Tensor(g, stop_gradient=True))
    return results
