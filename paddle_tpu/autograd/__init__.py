"""Autograd package (reference: paddle.autograd, imperative engine)."""
from .tape import (apply, backward, enable_grad, grad,  # noqa: F401
                   is_grad_enabled, no_grad, set_grad_enabled)

PyLayer = None  # custom-op style autograd extension: see paddle_tpu.incubate
