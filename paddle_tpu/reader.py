"""Reader decorators (reference: python/paddle/reader/decorator.py —
the v1 generator-combinator API kept alive in 2.0: map_readers, shuffle,
chain, compose, buffered, firstn; plus paddle.batch in batch.py).

These are plain-python generator transforms; the performant path is
paddle_tpu.io.DataLoader (native prefetch engine), but the combinators
remain for API parity and quick scripting.
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Empty, Queue
from threading import Event, Thread

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "cache"]


def cache(reader):
    all_data = list(reader())

    def __impl__():
        yield from all_data

    return __impl__


def map_readers(func, *readers):
    def __impl__():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return __impl__


def shuffle(reader, buf_size):
    def __impl__():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return __impl__


def chain(*readers):
    def __impl__():
        yield from itertools.chain(*[r() for r in readers])

    return __impl__


class ComposeNotAligned(ValueError):
    """reference: reader/decorator.py ComposeNotAligned."""


def compose(*readers, check_alignment=True):
    def __impl__():
        sentinel = object()
        iters = [iter(r()) for r in readers]
        while True:
            items = [next(it, sentinel) for it in iters]
            done = [it is sentinel for it in items]
            if all(done):
                return
            if any(done):
                if check_alignment:
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return __impl__


def buffered(reader, size):
    """Background-thread prefetch (reference decorator.py buffered).

    Cancellation-safe: a consumer that abandons the generator early
    (``close()``, ``break``, garbage collection) must not leave the fill
    thread blocked forever on a full queue holding the upstream reader
    open. The finally-block sets a stop flag and DRAINS the queue — the
    one blocked ``put`` completes, the producer sees the flag, closes
    the upstream generator, and exits."""
    end = object()

    def __impl__():
        q: Queue = Queue(maxsize=size)
        stop = Event()

        def fill():
            it = None
            try:
                # reader() itself may raise (eager file open): inside
                # the try, so the consumer gets the exception instead
                # of hanging forever on an empty queue
                it = reader()
                for item in it:
                    q.put(item)
                    if stop.is_set():
                        return
                q.put(end)
            except BaseException as e:  # surface, never hang the consumer
                q.put(e)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()

        t = Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is end:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # unblock a producer stuck in q.put: flag first, then drain
            # (after the drain, at most one more put succeeds, after
            # which the producer observes the flag and exits)
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except Empty:
                    break
            t.join(timeout=5.0)

    return __impl__


def firstn(reader, n):
    def __impl__():
        yield from itertools.islice(reader(), n)

    return __impl__
