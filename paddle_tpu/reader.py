"""Reader decorators (reference: python/paddle/reader/decorator.py —
the v1 generator-combinator API kept alive in 2.0: map_readers, shuffle,
chain, compose, buffered, firstn; plus paddle.batch in batch.py).

These are plain-python generator transforms; the performant path is
paddle_tpu.io.DataLoader (native prefetch engine), but the combinators
remain for API parity and quick scripting.
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "cache"]


def cache(reader):
    all_data = list(reader())

    def __impl__():
        yield from all_data

    return __impl__


def map_readers(func, *readers):
    def __impl__():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return __impl__


def shuffle(reader, buf_size):
    def __impl__():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return __impl__


def chain(*readers):
    def __impl__():
        yield from itertools.chain(*[r() for r in readers])

    return __impl__


class ComposeNotAligned(ValueError):
    """reference: reader/decorator.py ComposeNotAligned."""


def compose(*readers, check_alignment=True):
    def __impl__():
        sentinel = object()
        iters = [iter(r()) for r in readers]
        while True:
            items = [next(it, sentinel) for it in iters]
            done = [it is sentinel for it in items]
            if all(done):
                return
            if any(done):
                if check_alignment:
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return __impl__


def buffered(reader, size):
    """Background-thread prefetch (reference decorator.py buffered)."""
    end = object()

    def __impl__():
        q: Queue = Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
                q.put(end)
            except BaseException as e:  # surface, never hang the consumer
                q.put(e)

        t = Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    return __impl__


def firstn(reader, n):
    def __impl__():
        yield from itertools.islice(reader(), n)

    return __impl__
