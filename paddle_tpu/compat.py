"""paddle.compat — py2/py3 text/number helpers kept for API parity
(reference: python/paddle/compat.py). Python-3-only semantics here; the
py2 branches of the reference are dead code on every supported runtime.
"""
from __future__ import annotations

__all__ = ["long_type", "to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

long_type = int


def _map(obj, fn, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_map(o, fn, inplace) for o in obj]
            return obj
        return [_map(o, fn, inplace) for o in obj]
    if isinstance(obj, set):
        vals = {_map(o, fn, False) for o in obj}
        if inplace:
            obj.clear()
            obj.update(vals)
            return obj
        return vals
    if isinstance(obj, dict):
        vals = {_map(k, fn, False): _map(v, fn, False)
                for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(vals)
            return obj
        return vals
    return fn(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """Decode bytes (possibly nested in list/set/dict) to str
    (reference compat.py:36)."""
    def one(o):
        return o.decode(encoding) if isinstance(o, bytes) else o

    return _map(obj, one, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Encode str (possibly nested in list/set/dict) to bytes
    (reference compat.py:132)."""
    def one(o):
        return o.encode(encoding) if isinstance(o, str) else o

    return _map(obj, one, inplace)


def round(x, d=0):  # noqa: A001
    """Py2-style round (away from zero at .5) — reference compat.py:217."""
    import math

    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
