"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability surface of the reference
(liwanfei999/Paddle, PaddlePaddle ~v2.0) re-designed TPU-first:
JAX/XLA is the compiler+executor, Pallas provides custom kernels,
jax.sharding/pjit provides the distributed runtime. See SURVEY.md for the
reference layer map this mirrors.

Top-level namespace mirrors `paddle.*` so reference users can switch.
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import autograd, compat, core, framework  # noqa: F401
from .autograd import enable_grad, grad, no_grad, set_grad_enabled  # noqa: F401
from .core import (CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace,  # noqa: F401
                   XPUPlace, get_default_dtype, get_flags,
                   is_compiled_with_cuda, is_compiled_with_tpu, seed,
                   set_default_dtype, set_flags)
from .core.place import device_count, get_device, set_device  # noqa: F401
from .core.rng import get_rng_state, set_rng_state  # noqa: F401
# the reference's CUDA-named rng accessors map to the device rng stream
from .core.rng import get_rng_state as get_cuda_rng_state  # noqa: F401
from .core.rng import set_rng_state as set_cuda_rng_state  # noqa: F401
from .device import get_cudnn_version, is_compiled_with_xpu  # noqa: F401
from .framework import ParamAttr, Parameter, Tensor, to_tensor  # noqa: F401
from .framework.lazy import LazyGuard  # noqa: F401
from .framework.printoptions import set_printoptions  # noqa: F401

# dtype names at top level (paddle.float32 style)
from .core.dtype import (bfloat16, bool_, complex64, complex128,  # noqa: F401
                         float16, float32, float64, int8, int16, int32, int64,
                         uint8)

# the op library — import * exposes every paddle.tensor op at top level,
# matching paddle's `from .tensor.math import *` pattern.
from . import tensor  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import (abs, all, any, max, min, pow, round, slice, sum)  # noqa: F401,A004

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import device  # noqa: F401,E402


def __getattr__(name):  # PEP 562: lazy fluid (it imports back into here)
    if name == "fluid":
        import importlib

        mod = importlib.import_module(".fluid", __name__)
        globals()["fluid"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from . import jit  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402
from . import static  # noqa: F401,E402
from .static import create_parameter  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import resilience  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import ops  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import batch as _batch_mod  # noqa: E402
from .batch import batch  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from .distributed.parallel import DataParallel  # noqa: F401,E402
from .framework.io import load, save  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .jit.api import to_static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import serving  # noqa: F401,E402

# paddle.disable_static / enable_static compat: this framework is always
# "dygraph" at the API level; jit/pjit is the static path.


def disable_static(place=None):
    return None


def enable_static():
    return None


def in_dynamic_mode() -> bool:
    return True


class NoGradGuard(no_grad):
    pass


def is_grad_enabled():
    from .autograd import tape

    return tape.is_grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    from .hapi.model_summary import summary as _summary

    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.model_summary import flops as _flops

    return _flops(net, input_size, custom_ops, print_detail)
