"""Functionalization of Layers — the bridge between the stateful Layer API
and jax's pure-function world.

This replaces the reference's entire dygraph-to-static subsystem
(reference: python/paddle/fluid/dygraph/dygraph_to_static/ — AST transforms,
program_translator.py:756): because Layers execute jnp ops on their
``_value``s, we can swap parameter values for jit tracers and trace
``forward`` directly; no source translation needed.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax

from ..framework.tensor import Tensor


def state_tensors(layer) -> Tuple[List[str], List[Tensor], List[str],
                                  List[Tensor]]:
    """Ordered (param_names, params, buffer_names, buffers)."""
    pn, pv = zip(*layer.named_parameters()) if \
        list(layer.named_parameters()) else ((), ())
    bn, bv = zip(*layer.named_buffers()) if \
        list(layer.named_buffers()) else ((), ())
    return list(pn), list(pv), list(bn), list(bv)


class _swapped_state:
    """Temporarily substitute tensor values (tracers) into live tensors.

    Same-thread NESTING is legal and common (the pipeline head re-swaps
    the head params inside the outer swap; LIFO restore keeps it exact).
    What is NOT legal is two THREADS swapping the same tensor — a second
    trainer tracing the same module concurrently would silently read the
    other trace's tracers. Each swap records its owning thread in a
    module-level registry and a cross-thread collision raises instead of
    corrupting the trace (VERDICT r3 weak #6)."""

    _owner: dict = {}                # id(tensor) -> (thread_id, depth)
    _owner_lock = threading.Lock()

    def __init__(self, tensors: List[Tensor], values):
        self.tensors = tensors
        self.values = values

    def __enter__(self):
        tid = threading.get_ident()
        # The registry bookkeeping must be atomic: without the lock two
        # threads can both pass the owner check (get-then-set race) and
        # both swap — the exact corruption this registry detects. And
        # validation must complete BEFORE any registration: a raise
        # mid-registration would leak permanent stale entries (no __exit__
        # runs when __enter__ raises).
        with _swapped_state._owner_lock:
            for t in self.tensors:
                owner = _swapped_state._owner.get(id(t))
                if owner is not None and owner[0] != tid:
                    raise RuntimeError(
                        "_swapped_state: tensor is already swapped by "
                        "another thread — two trainers/traces are "
                        "functionalizing the same module concurrently. "
                        "Build separate module instances per trainer "
                        "(shared Layer objects cannot be traced from two "
                        "threads at once).")
            for t in self.tensors:
                owner = _swapped_state._owner.get(id(t))
                _swapped_state._owner[id(t)] = (
                    tid, 1 if owner is None else owner[1] + 1)
        self.saved = [t._value for t in self.tensors]
        for t, v in zip(self.tensors, self.values):
            t._value = v
        return self

    def __exit__(self, *exc):
        for t, v in zip(self.tensors, self.saved):
            t._value = v
        with _swapped_state._owner_lock:
            for t in self.tensors:
                owner = _swapped_state._owner.get(id(t))
                if owner is not None:
                    if owner[1] <= 1:
                        del _swapped_state._owner[id(t)]
                    else:
                        _swapped_state._owner[id(t)] = (owner[0],
                                                        owner[1] - 1)
        return False


def functional_call(layer, param_values, buffer_values, args,
                    training: Optional[bool] = None, rng_key=None):
    """Run ``layer.forward`` with the given state values, purely.

    Returns (outputs, new_buffer_values). Output Tensors are unwrapped to raw
    values. Safe to call under jax tracing.
    """
    from ..core import rng

    pn, pt, bn, bt = state_tensors(layer)
    prev_mode = layer.training
    if training is not None and training != prev_mode:
        layer.train() if training else layer.eval()
    try:
        with _swapped_state(pt + bt, list(param_values) + list(buffer_values)):
            if rng_key is not None:
                with rng.key_scope(rng_key):
                    out = layer(*args)
            else:
                out = layer(*args)
            new_buffers = [t._value for t in bt]
        out_vals = jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))
        return out_vals, new_buffers
    finally:
        if training is not None and training != prev_mode:
            layer.train() if prev_mode else layer.eval()
