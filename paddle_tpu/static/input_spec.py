"""InputSpec (reference: python/paddle/static/input.py)."""
from __future__ import annotations


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"
