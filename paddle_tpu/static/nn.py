"""Control-flow ops (reference: paddle/fluid/operators/controlflow/ —
conditional_block_op.cc, while_op.cc re-entering the Executor on
sub-blocks; python surface fluid/layers/control_flow.py cond/while_loop/
case/switch_case).

TPU-native translation (SURVEY §7): sub-block re-execution becomes
lax.cond / lax.while_loop — ONE compiled program, both branches staged,
no host round-trip per iteration. Tape-level (Tensor in/out) via apply.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..tensor._helper import apply

# paddle.static.nn is also the 2.x home of the sequence (LoD) op family
# (reference: python/paddle/fluid/layers/sequence_lod.py, re-exported as
# paddle.static.nn.sequence_*)
from ..nn.functional.sequence_lod import (sequence_mask, sequence_pad,  # noqa: F401,E402
                                          sequence_unpad, sequence_pool,
                                          sequence_first_step,
                                          sequence_last_step,
                                          sequence_expand, sequence_expand_as,
                                          sequence_concat, sequence_softmax,
                                          sequence_reverse, sequence_conv,
                                          sequence_enumerate, sequence_slice,
                                          sequence_erase, sequence_reshape,
                                          sequence_scatter,
                                          sequence_topk_avg_pooling)

__all__ = ["cond", "while_loop", "case", "switch_case",
           "sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_first_step", "sequence_last_step",
           "sequence_expand", "sequence_expand_as", "sequence_concat",
           "sequence_softmax", "sequence_reverse", "sequence_conv",
           "sequence_enumerate", "sequence_slice", "sequence_erase",
           "sequence_reshape", "sequence_scatter",
           "sequence_topk_avg_pooling"]


def _tensors_in(vals):
    return [v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
            for v in vals]


def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference: fluid/layers/control_flow.py cond — both branches are
    traced (XLA conditional); functions take no args and may close over
    Tensors (captured as jax constants in the trace)."""
    def f(p):
        t = true_fn()
        fo = false_fn()
        t_leaves = jax.tree_util.tree_leaves(
            t, is_leaf=lambda x: isinstance(x, Tensor))
        f_leaves = jax.tree_util.tree_leaves(
            fo, is_leaf=lambda x: isinstance(x, Tensor))
        tv = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
              for x in t_leaves]
        fv = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
              for x in f_leaves]
        out = jax.lax.cond(jnp.reshape(p, ()), lambda: tv, lambda: fv)
        return out[0] if len(out) == 1 else tuple(out)

    return apply(f, pred if isinstance(pred, Tensor)
                 else Tensor(jnp.asarray(pred)), name="cond")


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """reference: fluid/layers/control_flow.py while_loop (WhileOp) —
    lax.while_loop; loop_vars is a list of Tensors."""
    lv = _tensors_in(loop_vars)

    def f(*vals):
        def c(vs):
            out = cond_fn(*[Tensor(v) for v in vs])
            return jnp.reshape(out._value if isinstance(out, Tensor)
                               else jnp.asarray(out), ())

        def b(vs):
            outs = body_fn(*[Tensor(v) for v in vs])
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            return tuple(o._value if isinstance(o, Tensor)
                         else jnp.asarray(o) for o in outs)

        res = jax.lax.while_loop(c, b, tuple(vals))
        return res[0] if len(res) == 1 else tuple(res)

    out = apply(f, *lv, name="while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def case(pred_fn_pairs, default=None, name=None):
    """reference: fluid/layers/control_flow.py case — first true pred
    wins; lowered to a chain of lax.cond selects."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    preds = [p for p, _ in pred_fn_pairs]

    def f(*pvals):
        outs = [fn() for _, fn in pred_fn_pairs]
        if default is not None:
            outs.append(default())
        vals = [o._value if isinstance(o, Tensor) else jnp.asarray(o)
                for o in outs]
        # fallback: the default when given, else the last branch
        result = vals[-1]
        # fold right: earlier preds take priority
        for p, v in zip(reversed(pvals), reversed(
                vals[:len(pvals)])):
            result = jnp.where(jnp.reshape(p, ()), v, result)
        return result

    return apply(f, *_tensors_in(preds), name="case")


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: fluid/layers/control_flow.py switch_case — jax.lax.switch."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        dense = all(k == i for i, k in enumerate(keys))
        fns = [branch_fns[k] for k in keys]
        if not dense:
            # sparse indices: map via where-chain
            def f(bi):
                outs = [fn() for fn in fns]
                dflt = default() if default is not None else outs[-1]
                vals = [o._value if isinstance(o, Tensor)
                        else jnp.asarray(o) for o in outs]
                dv = dflt._value if isinstance(dflt, Tensor) \
                    else jnp.asarray(dflt)
                result = dv
                for k, v in zip(keys, vals):
                    result = jnp.where(jnp.reshape(bi, ()) == k, v, result)
                return result

            return apply(f, branch_index if isinstance(branch_index, Tensor)
                         else Tensor(jnp.asarray(branch_index)),
                         name="switch_case")
    else:
        fns = list(branch_fns)
    if default is not None:
        fns = fns + [default]

    def f(bi):
        vals = [lambda fn=fn: [
            x._value if isinstance(x, Tensor) else jnp.asarray(x)
            for x in jax.tree_util.tree_leaves(
                fn(), is_leaf=lambda x: isinstance(x, Tensor))]
            for fn in fns]
        idx = jnp.clip(jnp.reshape(bi, ()).astype(jnp.int32), 0,
                       len(fns) - 1)
        out = jax.lax.switch(idx, vals)
        return out[0] if len(out) == 1 else tuple(out)

    return apply(f, branch_index if isinstance(branch_index, Tensor)
                 else Tensor(jnp.asarray(branch_index)), name="switch_case")
