"""Static-graph analogue layer.

The reference's static graph (ProgramDesc + Executor, reference:
paddle/fluid/framework/framework.proto, executor.cc) maps to traced
jaxprs compiled by XLA. This package holds the functionalization bridge
plus thin compat names (InputSpec, Program-like plan objects).
"""
from .functional import functional_call, state_tensors  # noqa: F401
from .input_spec import InputSpec  # noqa: F401


class Program:
    """Compat shell: the serialized unit on TPU is (module, mesh, shardings).

    Real graph capture/serialization is jit.save's StableHLO export."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()
