"""Static-graph analogue layer.

The reference's static graph (ProgramDesc + Executor, reference:
paddle/fluid/framework/framework.proto, executor.cc) maps to traced
jaxprs compiled by XLA. This package holds the functionalization bridge
plus thin compat names (InputSpec, Program-like plan objects).
"""
from . import nn  # noqa: F401
from .functional import functional_call, state_tensors  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .plan import Plan  # noqa: F401


class Program:
    """The serialized-program unit, backed by a Plan (static/plan.py —
    module bytes + mesh + shardings; the ProgramDesc analogue per SURVEY
    §7). ``Program.from_function`` captures one; block/op introspection
    of the reference maps to the StableHLO text (``as_text``)."""

    def __init__(self, plan: "Plan" = None):
        self.plan = plan

    @classmethod
    def from_function(cls, fn, example_args, **kw):
        return cls(Plan.trace(fn, example_args, **kw))

    def run(self, *args):
        if self.plan is None:
            raise ValueError("empty Program: build with from_function")
        return self.plan(*args)

    def save(self, path):
        if self.plan is None:
            raise ValueError("empty Program")
        self.plan.save(path)

    @classmethod
    def load(cls, path):
        return cls(Plan.load(path))

    def as_text(self):
        return self.plan.as_text() if self.plan is not None else ""

    def global_block(self):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data — a typed graph input placeholder (reference:
    python/paddle/fluid/data.py). The TPU translation is an InputSpec:
    hand it to jit.to_static/save as the traced signature."""
    return InputSpec(shape, dtype, name)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients (reference fluid/backward.py
    calc_gradient): grads of ``targets`` w.r.t. ``inputs`` — here the
    eager tape computes them directly (no program rewriting)."""
    from ..autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone trainable parameter (reference:
    python/paddle/fluid/layers/tensor.py create_parameter; also exported
    as ``paddle.create_parameter``). Delegates to the same resolution as
    Layer.create_parameter (nn/layer/layers.py build_parameter)."""
    from ..framework.param_attr import ParamAttr
    from ..nn.layer.layers import build_parameter

    return build_parameter(shape, attr if attr is not None else ParamAttr(),
                           dtype, is_bias, default_initializer, name=name)
