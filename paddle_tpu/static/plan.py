"""The serializable "plan" layer — this framework's ProgramDesc analogue.

SURVEY §7 translation table: "ProgramDesc protobuf IR + C++ executors →
traced jaxpr/StableHLO; XLA is the executor. Keep a thin, serializable
'plan' layer (module + mesh + shardings) as our Program analogue"
(reference: framework/framework.proto:42-207 ProgramDesc — the serialized
unit for executors, distributed rewriters, inference, and save/load).

A Plan captures:
  - the traced computation as a jax.export portable artifact (versioned
    StableHLO bytes — runnable in another process, SURVEY §4's
    "serialized unit"),
  - the mesh axis names/shape it was traced for,
  - the input/output sharding specs (as strings, for inspection).

jit.save/inference.Predictor use the same artifact for model programs;
Plan is the general-purpose unit (any jittable function, any shardings).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Sequence

import jax
import numpy as np

__all__ = ["Plan"]


class Plan:
    def __init__(self, exported, mesh_shape: dict, meta: dict):
        self._exported = exported
        self.mesh_shape = dict(mesh_shape)
        self.meta = dict(meta)

    # -- construction ------------------------------------------------------
    @classmethod
    def trace(cls, fn, example_args: Sequence[Any],
              mesh: Optional[jax.sharding.Mesh] = None,
              in_shardings=None, out_shardings=None,
              static_argnums=()) -> "Plan":
        """Trace fn once on example args (arrays or ShapeDtypeStructs) and
        capture the compiled plan."""
        from jax import export as jax_export

        jit_kw = {}
        if in_shardings is not None:
            jit_kw["in_shardings"] = in_shardings
        if out_shardings is not None:
            jit_kw["out_shardings"] = out_shardings
        jfn = jax.jit(fn, static_argnums=static_argnums, **jit_kw)
        specs = [a if isinstance(a, jax.ShapeDtypeStruct)
                 else jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
                 for a in example_args]
        exported = jax_export.export(jfn)(*specs)
        mesh_shape = dict(mesh.shape) if mesh is not None else {}
        meta = {
            "in_avals": [(list(s.shape), str(s.dtype)) for s in specs],
            "in_shardings": [str(s) for s in getattr(
                exported, "in_shardings_hlo", ())],
            "out_shardings": [str(s) for s in getattr(
                exported, "out_shardings_hlo", ())],
            "nr_devices": getattr(exported, "nr_devices", 1),
        }
        return cls(exported, mesh_shape, meta)

    # -- execution ---------------------------------------------------------
    def __call__(self, *args):
        return self._exported.call(*args)

    run = __call__

    # -- serialization (the ProgramDesc save/load analogue) ---------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".plan", "wb") as f:
            pickle.dump({"mesh_shape": self.mesh_shape, "meta": self.meta,
                         "module": bytes(self._exported.serialize())}, f)

    @classmethod
    def load(cls, path: str) -> "Plan":
        from jax import export as jax_export

        with open(path + ".plan", "rb") as f:
            d = pickle.load(f)
        exported = jax_export.deserialize(bytearray(d["module"]))
        return cls(exported, d["mesh_shape"], d["meta"])

    # -- inspection --------------------------------------------------------
    def as_text(self) -> str:
        """StableHLO text of the captured module (the analogue of
        printing a ProgramDesc)."""
        return str(self._exported.mlir_module())

    def __repr__(self):
        return (f"Plan(devices={self.meta.get('nr_devices', 1)}, "
                f"mesh={self.mesh_shape}, "
                f"inputs={self.meta.get('in_avals')})")
