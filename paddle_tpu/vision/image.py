"""Image backend selection (reference: python/paddle/vision/image.py).

The reference multiplexes PIL vs OpenCV loaders; this stack decodes via
numpy (vision/transforms operate on arrays), so the backend registry
keeps API parity and validates names.
"""
_image_backend = "pil"

__all__ = ["set_image_backend", "get_image_backend", "image_load"]


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but "
            f"got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file as the backend's native type (numpy array
    here; PIL if installed and selected)."""
    backend = backend or _image_backend
    if backend == "pil":
        try:
            from PIL import Image

            return Image.open(path)
        except ImportError:
            pass
    import numpy as np

    with open(path, "rb") as f:
        data = f.read()
    try:
        from PIL import Image
        import io as _io

        return np.asarray(Image.open(_io.BytesIO(data)))
    except ImportError as e:
        raise RuntimeError(
            "no image decoder available (PIL not installed); pass "
            "arrays directly to vision.transforms") from e
