"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, flowers.py…). Zero-egress environment: loaders parse the REAL
file formats when files are present (MNIST idx-gzip, reference
vision/datasets/mnist.py:117-143; CIFAR python-pickle tarball, reference
vision/datasets/cifar.py:112-135; Flowers jpg-tgz + .mat; VOC2012
trainval tar). Without files they RAISE unless the caller explicitly
opts into a deterministic synthetic set with ``synthetic_size=N`` —
silent fake data is never served (round-3 policy, io.synthetic_optin).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset, synthetic_optin as _synthetic_optin

_MNIST_DIR_CANDIDATES = ("train-images-idx3-ubyte.gz",
                         "t10k-images-idx3-ubyte.gz")


def _find_mnist_files(root, mode):
    stem = "train" if mode == "train" else "t10k"
    img = os.path.join(root, f"{stem}-images-idx3-ubyte.gz")
    lbl = os.path.join(root, f"{stem}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lbl):
        return img, lbl
    return None, None


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py. Parses the real idx format
    (magic 2051/2049, big-endian headers, gzip) from `image_path`/
    `label_path` or a directory of standard file names; requires an explicit synthetic_size opt-in when files are absent."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None, root=None):
        self.mode = mode
        self.transform = transform
        if root and not image_path:
            image_path, label_path = _find_mnist_files(root, mode)
        if image_path and os.path.exists(image_path):
            opener = gzip.open if image_path.endswith(".gz") else open
            with opener(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                if magic != 2051:
                    raise ValueError(
                        f"{image_path}: bad idx3 magic {magic} (want 2051)")
                self.images = np.frombuffer(
                    f.read(n * rows * cols), np.uint8).reshape(n, rows, cols)
            opener = gzip.open if label_path.endswith(".gz") else open
            with opener(label_path, "rb") as f:
                magic, n2 = struct.unpack(">II", f.read(8))
                if magic != 2049:
                    raise ValueError(
                        f"{label_path}: bad idx1 magic {magic} (want 2049)")
                self.labels = np.frombuffer(f.read(n2), np.uint8)
            if len(self.labels) != len(self.images):
                raise ValueError(
                    f"mnist: {len(self.images)} images vs "
                    f"{len(self.labels)} labels")
        else:
            n = _synthetic_optin("MNIST", synthetic_size,
                                 6000 if mode == "train" else 1000)
            r = np.random.RandomState(42 if mode == "train" else 43)
            self.labels = r.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so a real model can actually learn
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, lbl in enumerate(self.labels):
                img = r.rand(28, 28) * 64
                row, col = divmod(int(lbl), 5)
                img[row * 12 + 2:row * 12 + 12, col * 5 + 1:col * 5 + 5] += 180
                self.images[i] = img.clip(0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 127.5 - 1.0
        lbl = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py — parses the real
    cifar-10-python.tar.gz (pickled dict batches: data [N, 3072] uint8
    row-major CHW, labels list) when `data_file` exists; synthetic
    fallback otherwise."""

    _label_key = b"labels"
    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            wanted = self._train_members if mode == "train" \
                else self._test_members
            images, labels = [], []
            with tarfile.open(data_file, "r:*") as tf:
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in wanted:
                        d = pickle.load(tf.extractfile(m),
                                        encoding="bytes")
                        images.append(np.asarray(d[b"data"], np.uint8)
                                      .reshape(-1, 3, 32, 32))
                        labels.extend(d[self._label_key])
            if not images:
                raise ValueError(
                    f"{data_file}: no {wanted} members found")
            self.images = np.concatenate(images, 0)
            self.labels = np.asarray(labels, np.int64)
            return
        n = _synthetic_optin(type(self).__name__, synthetic_size,
                             5000 if mode == "train" else 1000)
        r = np.random.RandomState(7 if mode == "train" else 8)
        self.labels = r.randint(0, 10, n).astype(np.int64)
        self.images = (r.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        for i, lbl in enumerate(self.labels):
            self.images[i, int(lbl) % 3, :8, :8] = 250

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    """cifar-100-python.tar.gz: one train/test member, fine_labels key."""

    _label_key = b"fine_labels"
    _train_members = ["train"]
    _test_members = ["test"]


class Flowers(Dataset):
    """Oxford 102 Flowers (reference: vision/datasets/flowers.py —
    102flowers.tgz of jpgs + imagelabels.mat + setid.mat). Real-format
    path: decodes the jpgs via PIL and the .mat files via scipy.io;
    synthetic opt-in otherwise. Yields (CHW float32 image, int64 label)
    like the reference's reader."""

    _splits = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import io as _io

            import scipy.io as sio
            from PIL import Image

            for nm, f in (("label_file", label_file),
                          ("setid_file", setid_file)):
                if not f or not os.path.exists(f):
                    raise ValueError(
                        f"Flowers: {nm} is required alongside data_file "
                        f"(got {f!r}) — imagelabels.mat / setid.mat from "
                        "the same release")
            labels = sio.loadmat(label_file)["labels"].ravel()
            ids = sio.loadmat(setid_file)[
                self._splits[mode]].ravel()
            wanted = {f"image_{int(i):05d}.jpg" for i in ids}
            by_name = {}
            with tarfile.open(data_file, "r:*") as tf:
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in wanted:          # skip the other ~7k jpgs
                        by_name[base] = tf.extractfile(m).read()
            self.images, self.labels = [], []
            for i in ids:
                raw = by_name.get(f"image_{int(i):05d}.jpg")
                if raw is None:
                    raise ValueError(
                        f"{data_file}: image_{int(i):05d}.jpg named by "
                        "setid.mat is missing from the archive")
                img = np.asarray(Image.open(_io.BytesIO(raw))
                                 .convert("RGB"), np.uint8)
                self.images.append(img.transpose(2, 0, 1))
                self.labels.append(int(labels[int(i) - 1]) - 1)  # 1-based
            self.labels = np.asarray(self.labels, np.int64)
            return
        n = _synthetic_optin("Flowers", synthetic_size, 1020)
        r = np.random.RandomState(11)
        self.labels = r.randint(0, 102, n).astype(np.int64)
        self.images = [(r.rand(3, 32, 32) * 255).astype(np.uint8)
                       for _ in range(n)]

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference:
    vision/datasets/voc2012.py — the trainval tar's JPEGImages +
    SegmentationClass pngs, split lists under ImageSets/Segmentation).
    Yields (CHW float32 image, HW int64 mask)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import io as _io

            from PIL import Image

            want = {"train": "train.txt", "valid": "val.txt",
                    "test": "val.txt", "val": "val.txt"}[mode]
            with tarfile.open(data_file, "r:*") as tf:
                members = tf.getmembers()
                split = [m for m in members if m.name.endswith(
                    f"ImageSets/Segmentation/{want}")]
                if not split:
                    raise ValueError(
                        f"{data_file}: ImageSets/Segmentation/{want} not "
                        "found — not a VOC trainval archive")
                names = tf.extractfile(split[0]).read().decode().split()
                in_split = set(names)
                # only the split's ~1.4k of the archive's ~17k images are
                # read — the full trainval tar is multiple GB of jpgs
                jpgs, pngs = {}, {}
                for m in members:
                    base = os.path.basename(m.name)
                    stem = base[:-4]
                    if stem not in in_split:
                        continue
                    if "/JPEGImages/" in m.name and base.endswith(".jpg"):
                        jpgs[stem] = tf.extractfile(m).read()
                    elif "/SegmentationClass/" in m.name and \
                            base.endswith(".png"):
                        pngs[stem] = tf.extractfile(m).read()
            self._pairs = []
            for n in names:
                if n not in jpgs or n not in pngs:
                    raise ValueError(
                        f"{data_file}: split {want} lists {n!r} but the "
                        "archive lacks its jpg or segmentation png — "
                        "truncated/partial archive")
                img = np.asarray(Image.open(_io.BytesIO(jpgs[n]))
                                 .convert("RGB"), np.uint8)
                mask = np.asarray(Image.open(_io.BytesIO(pngs[n])),
                                  np.uint8)
                # masks stay uint8 until __getitem__ (int64 is 8x the
                # resident memory over a full VOC split)
                self._pairs.append((img.transpose(2, 0, 1), mask))
            return
        n = _synthetic_optin("VOC2012", synthetic_size, 128)
        r = np.random.RandomState(13)
        self._pairs = [((r.rand(3, 32, 32) * 255).astype(np.uint8),
                        r.randint(0, 21, (32, 32)).astype(np.uint8))
                       for _ in range(n)]

    def __getitem__(self, idx):
        img, mask = self._pairs[idx]
        img = img.astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, mask.astype(np.int64)

    def __len__(self):
        return len(self._pairs)
