"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, flowers.py…). Zero-egress environment: loaders read local files
when present and can synthesize deterministic data for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py. Reads idx-format files from
    `image_path`/`label_path`; falls back to a deterministic synthetic set
    when files are absent (download is impossible here)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                _, n = struct.unpack(">II", f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = synthetic_size or (6000 if mode == "train" else 1000)
            r = np.random.RandomState(42 if mode == "train" else 43)
            self.labels = r.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so a real model can actually learn
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, lbl in enumerate(self.labels):
                img = r.rand(28, 28) * 64
                row, col = divmod(int(lbl), 5)
                img[row * 12 + 2:row * 12 + 12, col * 5 + 1:col * 5 + 5] += 180
                self.images[i] = img.clip(0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 127.5 - 1.0
        lbl = np.asarray(self.labels[idx], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py. Synthetic fallback as above."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        n = synthetic_size or (5000 if mode == "train" else 1000)
        r = np.random.RandomState(7 if mode == "train" else 8)
        self.labels = r.randint(0, 10, n).astype(np.int64)
        self.images = (r.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        for i, lbl in enumerate(self.labels):
            self.images[i, int(lbl) % 3, :8, :8] = 250

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass
