"""paddle.vision equivalent."""
from . import datasets, models, ops, transforms  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401


def set_image_backend(backend):
    return None


def get_image_backend():
    return "numpy"
