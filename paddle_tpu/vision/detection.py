"""Detection op pipeline: the reference's operators/detection/ family.

TPU-native rebuild of the ~20-op detection suite (reference:
paddle/fluid/operators/detection/*.cc; python surface
python/paddle/fluid/layers/detection.py). Split by nature:

* **Jittable JAX** — elementwise / gather / matrix ops with static shapes:
  iou_similarity, box_coder, box_clip, polygon_box_transform,
  target_assign, anchor_generator, density_prior_box, and the NMS *cores*
  (pairwise-IoU matrix + lax.scan suppression for hard NMS; fully
  vectorized decay for matrix NMS). These run on the VPU/MXU.
* **Host orchestration** — ops whose OUTPUT ROW COUNT is data-dependent
  (multiclass_nms, generate_proposals, bipartite_match,
  rpn_target_assign, FPN redistribution, ...). The reference registers
  these CPU-only too (e.g. multiclass_nms_op.cc GetExpectedKernelType
  pins CPUPlace): they are the variable-shape tail between two fixed-
  shape device graphs. Here the O(M^2) IoU/suppression math still runs
  on device via the JAX cores; only selection/packing is host numpy.

Conventions (dense-ragged, like nn/functional/sequence_lod.py): LoD
batching is expressed as an explicit ``rois_num``/``lengths`` vector next
to a packed or padded tensor.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from functools import partial

from ..core import rng as _core_rng
from ..framework.tensor import Tensor
from ..tensor._helper import apply, unwrap

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "polygon_box_transform",
    "anchor_generator", "density_prior_box", "bipartite_match",
    "target_assign", "multiclass_nms", "matrix_nms", "locality_aware_nms",
    "generate_proposals", "rpn_target_assign", "mine_hard_examples",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "retinanet_detection_output", "generate_proposal_labels",
]


# ---------------------------------------------------------------------------
# jittable cores
# ---------------------------------------------------------------------------
def _iou_matrix(a, b, normalized=True, eps=1e-10):
    """Pairwise IoU [N,4] x [M,4] -> [N,M] (reference:
    detection/iou_similarity_op.h IOUSimilarity; +1 width/height when the
    boxes are unnormalized pixel coords, like the reference)."""
    off = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area_a = (ay2 - ay1 + off) * (ax2 - ax1 + off)
    area_b = (by2 - by1 + off) * (bx2 - bx1 + off)
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter + eps)


@partial(jax.jit, static_argnames=("normalized",))
def _hard_nms_keep(boxes, scores, iou_threshold, normalized=True):
    """Greedy hard-NMS keep mask over score-DESCENDING order — jittable,
    static [M] shapes (reference: multiclass_nms_op.cc NMSFast, eta==1).

    Returns (keep_mask[M] over the ORIGINAL index space, order[M]).
    The sequential data dependence (keep_i needs keep_j for j<i) is a
    length-M lax.scan over rows of the precomputed IoU matrix — the
    O(M^2) IoU math is one batched VPU op, only the scan is serial.
    """
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_matrix(b, b, normalized=normalized)

    def step(kept, row_i):
        iou_row, i = row_i
        suppressed = jnp.any(kept & (iou_row > iou_threshold))
        keep_i = ~suppressed
        return kept.at[i].set(keep_i), keep_i

    kept0 = jnp.zeros(b.shape[0], bool)
    _, keep_sorted = jax.lax.scan(
        step, kept0, (iou, jnp.arange(b.shape[0])))
    keep = jnp.zeros(b.shape[0], bool).at[order].set(keep_sorted)
    return keep, order


@partial(jax.jit, static_argnames=("use_gaussian", "normalized"))
def _matrix_nms_decay(boxes, scores, sigma, use_gaussian=False,
                      normalized=True):
    """Matrix-NMS decayed scores over score-descending order — fully
    vectorized, no serial loop (reference: matrix_nms_op.cc NMSMatrix).
    Returns decayed scores aligned with the ORIGINAL index order."""
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    m = b.shape[0]
    iou = _iou_matrix(b, b, normalized=normalized)
    tri = jnp.tril(jnp.ones((m, m), bool), -1)       # j < i
    iou_lower = jnp.where(tri, iou, 0.0)
    # iou_max[j] = max_{k<j} iou[j,k]
    iou_max = jnp.max(iou_lower, axis=1)
    if use_gaussian:
        decay = jnp.exp((iou_max[None, :] ** 2 - iou_lower ** 2) / sigma)
    else:
        decay = (1.0 - iou_lower) / (1.0 - iou_max[None, :] + 1e-12)
    decay = jnp.where(tri, decay, 1.0)
    min_decay = jnp.min(decay, axis=1)
    ds = s * min_decay
    return jnp.zeros_like(scores).at[order].set(ds)


# ---------------------------------------------------------------------------
# jittable public ops
# ---------------------------------------------------------------------------
def iou_similarity(x, y, box_normalized=True, name=None):
    """[N,4] x [M,4] -> [N,M] IoU (reference:
    detection/iou_similarity_op.cc; python fluid/layers/detection.py:764)."""
    return apply(lambda a, b: _iou_matrix(a, b, normalized=box_normalized),
                 x, y, name="iou_similarity")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """Encode/decode boxes against priors (reference:
    detection/box_coder_op.h; python fluid/layers/detection.py:818).

    encode: target [N,4] x prior [M,4] -> [N,M,4]
    decode: target [N,M,4] x prior [M,4] (axis=0) or [N,4] (axis=1)
            -> [N,M,4]
    ``prior_box_var`` is a [M,4] tensor, a 4-list, or None.
    """
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(f"box_coder: bad code_type {code_type}")
    off = 0.0 if box_normalized else 1.0
    var_list = None
    var_is_tensor = isinstance(prior_box_var, (Tensor, jnp.ndarray,
                                               np.ndarray))
    if prior_box_var is None:
        pass
    elif not var_is_tensor:
        var_list = np.asarray(list(prior_box_var), np.float32)
        if var_list.shape != (4,):
            raise ValueError("box_coder: variance list must have 4 entries")

    def f(p, t, *rest):
        pv = rest[0] if rest else None
        pw = p[:, 2] - p[:, 0] + off
        ph = p[:, 3] - p[:, 1] + off
        pcx = p[:, 0] + pw / 2
        pcy = p[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tcx = (t[:, 0] + t[:, 2]) / 2
            tcy = (t[:, 1] + t[:, 3]) / 2
            tw = t[:, 2] - t[:, 0] + off
            th = t[:, 3] - t[:, 1] + off
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)    # [N, M, 4]
            if pv is not None:
                out = out / pv[None, :, :]
            elif var_list is not None:
                out = out / jnp.asarray(var_list)
            return out
        # decode: t is [N, M, 4]; prior broadcasts along `axis`
        exp = (lambda v: v[None, :]) if axis == 0 else (lambda v: v[:, None])
        if pv is not None:
            var = pv[None, :, :] if axis == 0 else pv[:, None, :]
        elif var_list is not None:
            var = jnp.asarray(var_list)[None, None, :]
        else:
            var = jnp.ones((1, 1, 4), t.dtype)
        tcx = var[..., 0] * t[..., 0] * exp(pw) + exp(pcx)
        tcy = var[..., 1] * t[..., 1] * exp(ph) + exp(pcy)
        tw = jnp.exp(var[..., 2] * t[..., 2]) * exp(pw)
        th = jnp.exp(var[..., 3] * t[..., 3]) * exp(ph)
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)

    args = (prior_box, target_box)
    if var_is_tensor:
        args = args + (prior_box_var,)
    return apply(f, *args, name="box_coder")


def box_clip(input, im_info, name=None):  # noqa: A002
    """Clip boxes to image extent (reference: detection/box_clip_op.h
    ClipTiledBoxes, is_scale=True: im dims are im_info/scale, clipped to
    [0, dim-1]). ``input`` [B, R, 4] or [R, 4] with im_info [B, 3]=
    (h, w, scale)."""
    def f(b, info):
        squeeze = b.ndim == 2
        if squeeze:
            b = b[None]
        im_h = jnp.round(info[:, 0] / info[:, 2])
        im_w = jnp.round(info[:, 1] / info[:, 2])
        wlim = (im_w - 1.0)[:, None]
        hlim = (im_h - 1.0)[:, None]
        out = jnp.stack([
            jnp.clip(b[..., 0], 0.0, wlim),
            jnp.clip(b[..., 1], 0.0, hlim),
            jnp.clip(b[..., 2], 0.0, wlim),
            jnp.clip(b[..., 3], 0.0, hlim)], axis=-1)
        return out[0] if squeeze else out

    return apply(f, input, im_info, name="box_clip")


def polygon_box_transform(input, name=None):  # noqa: A002
    """EAST-style geometry map -> corner offsets (reference:
    detection/polygon_box_transform_op.cc): even geometry channels
    become 4*x_index - v, odd channels 4*y_index - v."""
    def f(v):
        n, g, h, w = v.shape
        if g % 2:
            raise ValueError("polygon_box_transform: channel dim must be "
                             "even")
        xs = jnp.arange(w, dtype=v.dtype) * 4.0
        ys = jnp.arange(h, dtype=v.dtype) * 4.0
        even = xs[None, None, None, :] - v
        odd = ys[None, None, :, None] - v
        is_even = (jnp.arange(g) % 2 == 0)[None, :, None, None]
        return jnp.where(is_even, even, odd)

    return apply(f, input, name="polygon_box_transform")


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,  # noqa: A002
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None,
                     offset=0.5, name=None):
    """Faster-RCNN anchors per feature-map position (reference:
    detection/anchor_generator_op.h; python detection.py:2399). Returns
    (anchors [H,W,A,4], variances [H,W,A,4]); the grid is a static
    function of the feature shape, computed as one vectorized expression.
    Order: aspect_ratios outer loop, anchor_sizes inner (reference
    kernel loop order)."""
    sizes = [float(s) for s in (anchor_sizes if isinstance(
        anchor_sizes, (list, tuple)) else [anchor_sizes])]
    ratios = [float(r) for r in (aspect_ratios if isinstance(
        aspect_ratios, (list, tuple)) else [aspect_ratios])]
    if not (isinstance(stride, (list, tuple)) and len(stride) == 2):
        raise ValueError("anchor_generator: stride must be a 2-list "
                         "(stride_w, stride_h)")
    sw, sh = float(stride[0]), float(stride[1])
    var = np.asarray(list(variance), np.float32)

    h, w = (int(input.shape[2]), int(input.shape[3]))
    # per-anchor base widths/heights (A = len(ratios)*len(sizes))
    ws, hs = [], []
    for ar in ratios:
        area = sw * sh
        base_w = round(np.sqrt(area / ar))
        base_h = round(base_w * ar)
        for size in sizes:
            ws.append(size / sw * base_w)
            hs.append(size / sh * base_h)
    ws = np.asarray(ws, np.float32)
    hs = np.asarray(hs, np.float32)
    xc = (np.arange(w, dtype=np.float32) * sw
          + offset * (sw - 1))[None, :, None]
    yc = (np.arange(h, dtype=np.float32) * sh
          + offset * (sh - 1))[:, None, None]
    anchors = np.stack(np.broadcast_arrays(
        xc - 0.5 * (ws - 1), yc - 0.5 * (hs - 1),
        xc + 0.5 * (ws - 1), yc + 0.5 * (hs - 1)), axis=-1)
    variances = np.broadcast_to(var, anchors.shape).copy()
    return Tensor(jnp.asarray(anchors)), Tensor(jnp.asarray(variances))


def density_prior_box(input, image, densities=None, fixed_sizes=None,  # noqa: A002
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """SSD density prior boxes (reference:
    detection/density_prior_box_op.h; python detection.py:1925)."""
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    fh, fw = int(input.shape[2]), int(input.shape[3])
    step_w = float(steps[0]) or img_w / fw
    step_h = float(steps[1]) or img_h / fh
    step_avg = int(0.5 * (step_w + step_h))
    densities = [int(d) for d in densities]
    fixed_sizes = [float(s) for s in fixed_sizes]
    fixed_ratios = [float(r) for r in fixed_ratios]

    boxes = []
    for s, density in zip(fixed_sizes, densities):
        shift = step_avg // density
        for r in fixed_ratios:
            bw = s * np.sqrt(r)
            bh = s / np.sqrt(r)
            di, dj = np.meshgrid(np.arange(density), np.arange(density),
                                 indexing="ij")
            # centers for every (h, w) grid position x (di, dj) sub-cell
            cx0 = (np.arange(fw) + offset) * step_w - step_avg / 2. \
                + shift / 2.
            cy0 = (np.arange(fh) + offset) * step_h - step_avg / 2. \
                + shift / 2.
            cx = cx0[None, :, None] + (dj.reshape(-1) * shift)[None, None, :]
            cy = cy0[:, None, None] + (di.reshape(-1) * shift)[None, None, :]
            box = np.stack(np.broadcast_arrays(
                np.maximum((cx - bw / 2.) / img_w, 0.),
                np.maximum((cy - bh / 2.) / img_h, 0.),
                np.minimum((cx + bw / 2.) / img_w, 1.),
                np.minimum((cy + bh / 2.) / img_h, 1.)), axis=-1)
            boxes.append(box)                         # [fh, fw, d^2, 4]
    out = np.concatenate(boxes, axis=2).astype(np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(list(variance), np.float32),
                          out.shape).copy()
    if flatten_to_2d:
        out = out.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def target_assign(input, matched_indices, negative_indices=None,  # noqa: A002
                  mismatch_value=None, input_lengths=None,
                  negative_lengths=None, name=None):
    """Assign matched rows of packed ``input`` to prediction slots
    (reference: detection/target_assign_op.h; python detection.py:1407).

    input: packed [total_rows, P, K] with ``input_lengths`` [B] rows per
    image (the reference's LoD); matched_indices: [B, M] (-1 = mismatch).
    Returns (out [B, M, K], out_weight [B, M, 1]).
    """
    if input_lengths is None:
        raise ValueError("target_assign: dense-ragged form requires "
                         "`input_lengths`")
    lens = np.asarray(unwrap(input_lengths)).astype(np.int64).reshape(-1)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]])

    def f(x, mi):
        if x.ndim == 2:
            x = x[:, None, :]
        p = x.shape[1]
        b, m = mi.shape
        off = jnp.asarray(offs)[:, None]
        idx = jnp.clip(off + jnp.maximum(mi, 0), 0, x.shape[0] - 1)
        w_off = jnp.arange(m)[None, :] % p
        out = x[idx, w_off]                           # [B, M, K]
        matched = (mi > -1)[..., None]
        mv = jnp.asarray(0 if mismatch_value is None else mismatch_value,
                         x.dtype)
        out = jnp.where(matched, out, mv)
        wt = matched.astype(jnp.float32)
        return out, wt

    out, wt = apply(f, input, matched_indices, name="target_assign")
    if negative_indices is not None:
        # NegTargetAssign (reference target_assign_op.h): negative slots
        # get out=mismatch_value, weight=1
        neg = np.asarray(unwrap(negative_indices)).astype(np.int64) \
            .reshape(-1)
        if negative_lengths is None and len(lens) > 1:
            raise ValueError(
                "target_assign: `negative_lengths` is required when the "
                "batch has more than one image — without it every "
                "negative index would be assigned to image 0")
        nlens = (np.asarray(unwrap(negative_lengths)).astype(np.int64)
                 .reshape(-1) if negative_lengths is not None
                 else np.asarray([len(neg)], np.int64))
        noffs = np.concatenate([[0], np.cumsum(nlens)])
        ov = np.asarray(unwrap(out)).copy()
        wv = np.asarray(unwrap(wt)).copy()
        mv = 0 if mismatch_value is None else mismatch_value
        for b in range(len(nlens)):
            for j in neg[noffs[b]:noffs[b + 1]]:
                ov[b, j, :] = mv
                wv[b, j, 0] = 1.0
        out, wt = Tensor(jnp.asarray(ov)), Tensor(jnp.asarray(wv))
    return out, wt


# Persistent sampling stream for the target-sampling ops: a fresh
# RandomState per call would redraw the SAME fg/bg subset every training
# step (the reference's engine RNG persists across invocations).
# paddle.seed() reseeds it via the core.rng registry.
_sample_rng = np.random.RandomState(0)
_core_rng.register_sample_rng(_sample_rng)


# ---------------------------------------------------------------------------
# host-orchestrated ops (variable-size outputs; reference kernels are
# CPU-only for the same reason)
# ---------------------------------------------------------------------------
def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    row_lengths=None, name=None):
    """Greedy bipartite (max-weight) matching of rows to columns
    (reference: detection/bipartite_match_op.cc BipartiteMatch — the
    sorted-pairs greedy variant). Returns (match_indices [B, C] int32
    col->row, match_dist [B, C]). ``row_lengths`` expresses the
    reference's LoD batching of the row axis."""
    d = np.asarray(unwrap(dist_matrix), np.float32)
    lens = (np.asarray(unwrap(row_lengths)).astype(np.int64).reshape(-1)
            if row_lengths is not None else np.asarray([d.shape[0]]))
    offs = np.concatenate([[0], np.cumsum(lens)])
    cols = d.shape[1]
    n = len(lens)
    mi = np.full((n, cols), -1, np.int32)
    md = np.zeros((n, cols), np.float32)
    for b in range(n):
        sub = d[offs[b]:offs[b + 1]]
        rows = sub.shape[0]
        order = np.argsort(-sub, axis=None)
        row_used = np.zeros(rows, bool)
        matched = 0
        for k in order:
            i, j = divmod(int(k), cols)
            if matched >= rows:
                break
            v = sub[i, j]
            if v <= 0:
                break
            if mi[b, j] == -1 and not row_used[i]:
                mi[b, j] = i
                md[b, j] = v
                row_used[i] = True
                matched += 1
        if match_type == "per_prediction":
            thr = 0.5 if dist_threshold is None else float(dist_threshold)
            for j in range(cols):
                if mi[b, j] != -1:
                    continue
                i = int(np.argmax(sub[:, j]))
                v = sub[i, j]
                if v >= thr and v > 1e-6:
                    mi[b, j] = i
                    md[b, j] = v
    return Tensor(jnp.asarray(mi)), Tensor(jnp.asarray(md))


def _iou_np(p, q, normalized=True):
    """Scalar IoU in plain numpy for the per-pair host loops (one device
    dispatch per 10-flop pair would dominate wall clock)."""
    off = 0.0 if normalized else 1.0
    aa = (p[2] - p[0] + off) * (p[3] - p[1] + off)
    ab = (q[2] - q[0] + off) * (q[3] - q[1] + off)
    iw = min(p[2], q[2]) - max(p[0], q[0]) + off
    ih = min(p[3], q[3]) - max(p[1], q[1]) + off
    inter = max(iw, 0.0) * max(ih, 0.0)
    return inter / (aa + ab - inter + 1e-10)


def _nms_select(boxes, scores, score_threshold, nms_threshold, top_k,
                eta=1.0, normalized=True):
    """Indices kept by hard NMS (host tail over the jittable core).
    boxes [M,4] scores [M] -> python list of kept indices, score-desc."""
    m = boxes.shape[0]
    if m == 0:
        return []
    cand = np.nonzero(scores > score_threshold)[0]
    if cand.size == 0:
        return []
    cand = cand[np.argsort(-scores[cand], kind="stable")]
    if top_k > -1:
        cand = cand[:top_k]
    if eta >= 1.0:
        # device core: IoU matrix + scan suppression
        keep, order = _hard_nms_keep(
            jnp.asarray(boxes[cand]), jnp.asarray(scores[cand]),
            jnp.float32(nms_threshold), normalized=normalized)
        keep = np.asarray(keep)
        order = np.asarray(order)
        return [int(cand[i]) for i in order if keep[i]]
    # adaptive-threshold path (eta < 1): serial host loop like NMSFast
    kept = []
    adaptive = nms_threshold
    bsel = boxes[cand]
    for i in range(len(cand)):
        ok = True
        for kj in kept:
            if _iou_np(bsel[i], boxes[kj],
                       normalized=normalized) > adaptive:
                ok = False
                break
        if ok:
            kept.append(int(cand[i]))
            if adaptive > 0.5:
                adaptive *= eta
    return kept


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   return_rois_num=True, name=None):
    """Per-class NMS + cross-class keep_top_k (reference:
    detection/multiclass_nms_op.cc; python detection.py:3262).

    bboxes [N, M, 4], scores [N, C, M] -> (out [No, 6], [index [No,1]],
    rois_num [N]). Out rows: [label, score, x1, y1, x2, y2]. The
    suppression core runs on device (_hard_nms_keep); assembly is host.
    """
    b = np.asarray(unwrap(bboxes), np.float32)
    s = np.asarray(unwrap(scores), np.float32)
    if b.ndim == 2:
        b = b[None]
    if s.ndim == 2:
        s = s[None]
    n, c, m = s.shape
    outs, idxs, nums = [], [], []
    for im in range(n):
        per_class = {}
        total = 0
        for cls in range(c):
            if cls == background_label:
                continue
            kept = _nms_select(b[im], s[im, cls], score_threshold,
                               nms_threshold, nms_top_k, eta=nms_eta,
                               normalized=normalized)
            if kept:
                per_class[cls] = kept
                total += len(kept)
        if keep_top_k > -1 and total > keep_top_k:
            pairs = [(s[im, cls, i], cls, i)
                     for cls, kk in per_class.items() for i in kk]
            pairs.sort(key=lambda t: -t[0])
            pairs = pairs[:keep_top_k]
            per_class = {}
            for _, cls, i in pairs:
                per_class.setdefault(cls, []).append(i)
            total = keep_top_k
        for cls in sorted(per_class):
            for i in per_class[cls]:
                outs.append([float(cls), s[im, cls, i]] +
                            b[im, i].tolist())
                idxs.append(im * m + i)
        nums.append(total)
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    ret = [Tensor(jnp.asarray(out))]
    if return_index:
        ret.append(Tensor(jnp.asarray(
            np.asarray(idxs, np.int32).reshape(-1, 1))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(ret) if len(ret) > 1 else ret[0]


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS — parallel score decay, no iterative suppression
    (reference: detection/matrix_nms_op.cc; python detection.py:3546).
    The decay is computed fully vectorized on device
    (_matrix_nms_decay); selection/packing is host."""
    b = np.asarray(unwrap(bboxes), np.float32)
    s = np.asarray(unwrap(scores), np.float32)
    if b.ndim == 2:
        b = b[None]
    if s.ndim == 2:
        s = s[None]
    n, c, m = s.shape
    outs, idxs, nums = [], [], []
    for im in range(n):
        rows = []                                     # (score, cls, idx)
        for cls in range(c):
            if cls == background_label:
                continue
            sc = s[im, cls]
            cand = np.nonzero(sc > score_threshold)[0]
            if cand.size == 0:
                continue
            cand = cand[np.argsort(-sc[cand], kind="stable")]
            if nms_top_k > -1:
                cand = cand[:nms_top_k]
            ds = np.asarray(_matrix_nms_decay(
                jnp.asarray(b[im][cand]), jnp.asarray(sc[cand]),
                jnp.float32(gaussian_sigma), use_gaussian=use_gaussian,
                normalized=normalized))
            for k, i in enumerate(cand):
                if ds[k] > post_threshold:
                    rows.append((float(ds[k]), cls, int(i)))
        rows.sort(key=lambda t: -t[0])
        if keep_top_k > -1:
            rows = rows[:keep_top_k]
        for score, cls, i in rows:
            outs.append([float(cls), score] + b[im, i].tolist())
            idxs.append(im * m + i)
        nums.append(len(rows))
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    ret = [Tensor(jnp.asarray(out))]
    if return_index:
        ret.append(Tensor(jnp.asarray(
            np.asarray(idxs, np.int32).reshape(-1, 1))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(ret) if len(ret) > 1 else ret[0]


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS for text detection (reference:
    detection/locality_aware_nms_op.cc): adjacent boxes with
    IoU > threshold are score-weight merged FIRST, then standard
    multiclass NMS runs on the merged set."""
    b = np.asarray(unwrap(bboxes), np.float32).copy()
    s = np.asarray(unwrap(scores), np.float32).copy()
    if b.ndim == 2:
        b = b[None]
    if s.ndim == 2:
        s = s[None]
    n, c, m = s.shape
    for im in range(n):
        for cls in range(c):
            if cls == background_label:
                continue
            idx = -1
            for i in range(m):
                if idx > -1:
                    iou = _iou_np(b[im, i], b[im, idx],
                                  normalized=normalized)
                    if iou > nms_threshold:
                        s1, s2 = s[im, cls, i], s[im, cls, idx]
                        b[im, idx] = (b[im, i] * s1 + b[im, idx] * s2) / \
                            max(s1 + s2, 1e-12)
                        s[im, cls, idx] += s1
                        s[im, cls, i] = 0.0
                    else:
                        idx = i
                else:
                    idx = i
    return multiclass_nms(Tensor(jnp.asarray(b)), Tensor(jnp.asarray(s)),
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          normalized=normalized, nms_eta=nms_eta,
                          background_label=background_label)


def _decode_proposals(anchors, deltas, variances=None, pixel_offset=True):
    """bbox_util.h BoxCoder (proposal flavor): anchors/deltas [M,4] ->
    proposals [M,4]; exp clipped at log(1000/16) like the reference."""
    clip = np.log(1000.0 / 16.0)
    off = 1.0 if pixel_offset else 0.0
    aw = anchors[:, 2] - anchors[:, 0] + off
    ah = anchors[:, 3] - anchors[:, 1] + off
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        dx = variances[:, 0] * deltas[:, 0]
        dy = variances[:, 1] * deltas[:, 1]
        dw = np.minimum(variances[:, 2] * deltas[:, 2], clip)
        dh = np.minimum(variances[:, 3] * deltas[:, 3], clip)
    else:
        dx, dy = deltas[:, 0], deltas[:, 1]
        dw = np.minimum(deltas[:, 2], clip)
        dh = np.minimum(deltas[:, 3], clip)
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = np.exp(dw) * aw
    h = np.exp(dh) * ah
    return np.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - off, cy + h / 2 - off], axis=1)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    """RPN proposal generation (reference:
    detection/generate_proposals_op.cc; python detection.py:2894).

    scores [N,A,H,W], bbox_deltas [N,4A,H,W], im_info [N,3],
    anchors/variances [H,W,A,4] -> (rpn_rois [R,4], rpn_roi_probs [R,1]
    [, rois_num [N]]). Steps per image: transpose to anchor-major, take
    pre_nms_top_n by score, decode (+1 pixel offsets), clip to image,
    filter tiny boxes, hard-NMS (device core), keep post_nms_top_n.
    """
    sc = np.asarray(unwrap(scores), np.float32)
    bd = np.asarray(unwrap(bbox_deltas), np.float32)
    info = np.asarray(unwrap(im_info), np.float32)
    anc = np.asarray(unwrap(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(unwrap(variances), np.float32).reshape(-1, 4)
    n, a, h, w = sc.shape
    rois_all, probs_all, nums = [], [], []
    for im in range(n):
        s = sc[im].transpose(1, 2, 0).reshape(-1)           # [H*W*A]
        d = bd[im].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s, kind="stable")
        if pre_nms_top_n > 0:
            order = order[:pre_nms_top_n]
        props = _decode_proposals(anc[order], d[order], var[order])
        im_h, im_w, im_scale = info[im]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, im_w - 1)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, im_h - 1)
        ws = (props[:, 2] - props[:, 0]) / im_scale + 1
        hs = (props[:, 3] - props[:, 1]) / im_scale + 1
        cx = props[:, 0] + (props[:, 2] - props[:, 0] + 1) / 2
        cy = props[:, 1] + (props[:, 3] - props[:, 1] + 1) / 2
        ms = max(float(min_size), 1.0)
        keep = (ws >= ms) & (hs >= ms) & (cx <= im_w) & (cy <= im_h)
        props = props[keep]
        sk = s[order][keep]
        kept = _nms_select(props, sk, -np.inf, nms_thresh, -1, eta=eta,
                           normalized=False)
        kept = kept[:post_nms_top_n] if post_nms_top_n > 0 else kept
        rois_all.append(props[kept])
        probs_all.append(sk[kept, None])
        nums.append(len(kept))
    rois = np.concatenate(rois_all, axis=0) if rois_all else \
        np.zeros((0, 4), np.float32)
    probs = np.concatenate(probs_all, axis=0) if probs_all else \
        np.zeros((0, 1), np.float32)
    out = (Tensor(jnp.asarray(rois)), Tensor(jnp.asarray(probs)))
    if return_rois_num:
        out = out + (Tensor(jnp.asarray(np.asarray(nums, np.int32))),)
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info, gt_lengths=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True, name=None):
    """RPN training targets (reference:
    detection/rpn_target_assign_op.cc; python detection.py:311).

    Dense-ragged form: gt_boxes packed [G,4] with ``gt_lengths`` [N]
    per-image counts (the reference's LoD); is_crowd packed [G].
    Returns (predicted_scores [F+B,1], predicted_location [F,4],
    target_label [F+B,1] int32, target_bbox [F,4],
    bbox_inside_weight [F,4]). Sampling is deterministic when
    use_random=False (first-k), numpy RandomState otherwise.
    """
    bp = np.asarray(unwrap(bbox_pred), np.float32)
    cl = np.asarray(unwrap(cls_logits), np.float32)
    anc = np.asarray(unwrap(anchor_box), np.float32)
    gts = np.asarray(unwrap(gt_boxes), np.float32)
    crowd = np.asarray(unwrap(is_crowd)).astype(np.int64).reshape(-1)
    info = np.asarray(unwrap(im_info), np.float32)
    lens = (np.asarray(unwrap(gt_lengths)).astype(np.int64).reshape(-1)
            if gt_lengths is not None else np.asarray([gts.shape[0]]))
    offs = np.concatenate([[0], np.cumsum(lens)])
    num_im = len(lens)
    anum = anc.shape[0]
    rng = _sample_rng

    loc_idx, score_idx, labels, tgt_bbox, inside_w = [], [], [], [], []
    for im in range(num_im):
        gt = gts[offs[im]:offs[im + 1]]
        cr = crowd[offs[im]:offs[im + 1]]
        gt = gt[cr == 0] if gt.size else gt
        im_h, im_w, im_scale = info[im]
        if rpn_straddle_thresh >= 0:
            inside = ((anc[:, 0] >= -rpn_straddle_thresh) &
                      (anc[:, 1] >= -rpn_straddle_thresh) &
                      (anc[:, 2] < im_w + rpn_straddle_thresh) &
                      (anc[:, 3] < im_h + rpn_straddle_thresh))
            cand = np.nonzero(inside)[0]
        else:
            cand = np.arange(anum)
        sub = anc[cand]
        if len(gt) == 0:
            lab = np.zeros(len(cand), np.int64)
            fg = np.zeros(0, np.int64)
            argmax_gt = np.zeros(len(cand), np.int64)
        else:
            iou = np.asarray(_iou_matrix(jnp.asarray(sub),
                                         jnp.asarray(gt),
                                         normalized=False))
            argmax_gt = iou.argmax(axis=1)
            max_iou = iou.max(axis=1)
            lab = np.full(len(cand), -1, np.int64)
            lab[max_iou < rpn_negative_overlap] = 0
            # (i) per-gt best anchor is positive
            best_per_gt = iou.max(axis=0)
            for g in range(len(gt)):
                lab[iou[:, g] >= best_per_gt[g] - 1e-5] = 1
            # (ii) IoU above positive threshold
            lab[max_iou >= rpn_positive_overlap] = 1
            fg = np.nonzero(lab == 1)[0]
        num_fg = int(rpn_fg_fraction * rpn_batch_size_per_im)
        if len(fg) > num_fg:
            drop = (rng.choice(fg, len(fg) - num_fg, replace=False)
                    if use_random else fg[num_fg:])
            lab[drop] = -1
            fg = np.nonzero(lab == 1)[0]
        bg = np.nonzero(lab == 0)[0]
        num_bg = rpn_batch_size_per_im - len(fg)
        if len(bg) > num_bg:
            keep = (rng.choice(bg, num_bg, replace=False)
                    if use_random else bg[:num_bg])
            lab[:] = np.where(lab == 1, 1, -1)
            lab[keep] = 0
            bg = np.nonzero(lab == 0)[0]
        for i in fg:
            loc_idx.append(im * anum + cand[i])
            score_idx.append(im * anum + cand[i])
            labels.append(1)
            if len(gt):
                g = gt[argmax_gt[i]]
                aw = sub[i, 2] - sub[i, 0] + 1
                ah = sub[i, 3] - sub[i, 1] + 1
                gw = g[2] - g[0] + 1
                gh = g[3] - g[1] + 1
                tgt_bbox.append([
                    (g[0] + gw / 2 - (sub[i, 0] + aw / 2)) / aw,
                    (g[1] + gh / 2 - (sub[i, 1] + ah / 2)) / ah,
                    np.log(gw / aw), np.log(gh / ah)])
                inside_w.append([1.0] * 4)
            else:
                tgt_bbox.append([0.0] * 4)
                inside_w.append([0.0] * 4)
        for i in bg:
            score_idx.append(im * anum + cand[i])
            labels.append(0)

    bp2 = bp.reshape(-1, 4)
    cl2 = cl.reshape(-1, 1)
    li = np.asarray(loc_idx, np.int64)
    si = np.asarray(score_idx, np.int64)
    return (Tensor(jnp.asarray(cl2[si])),
            Tensor(jnp.asarray(bp2[li])),
            Tensor(jnp.asarray(np.asarray(labels, np.int32)
                               .reshape(-1, 1))),
            Tensor(jnp.asarray(np.asarray(tgt_bbox, np.float32)
                               .reshape(-1, 4))),
            Tensor(jnp.asarray(np.asarray(inside_w, np.float32)
                               .reshape(-1, 4))))


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative",
                       name=None):
    """OHEM negative mining for SSD (reference:
    detection/mine_hard_examples_op.cc). Returns
    (neg_indices packed [K,1] int32, neg_lengths [N],
    updated_match_indices [N,M])."""
    cl = np.asarray(unwrap(cls_loss), np.float32)
    mi = np.asarray(unwrap(match_indices)).astype(np.int64)
    md = np.asarray(unwrap(match_dist), np.float32)
    ll = (np.asarray(unwrap(loc_loss), np.float32)
          if loc_loss is not None else None)
    n, m = mi.shape
    upd = mi.copy()
    neg_all, neg_lens = [], []
    for b in range(n):
        if mining_type == "max_negative":
            elig = np.nonzero((mi[b] == -1) &
                              (md[b] < neg_dist_threshold))[0]
            loss = cl[b, elig]
            num_pos = int((mi[b] != -1).sum())
            neg_sel = min(int(num_pos * neg_pos_ratio), len(elig))
        elif mining_type == "hard_example":
            elig = np.arange(m)
            loss = cl[b] + (ll[b] if ll is not None else 0.0)
            neg_sel = min(int(sample_size), m)
        else:
            elig = np.zeros(0, np.int64)
            loss = np.zeros(0, np.float32)
            neg_sel = 0
        order = np.argsort(-loss, kind="stable")[:neg_sel]
        sel = set(int(elig[k]) for k in order)
        negs = []
        if mining_type == "hard_example":
            for j in range(m):
                if mi[b, j] > -1:
                    if j not in sel:
                        upd[b, j] = -1
                elif j in sel:
                    negs.append(j)
        else:
            negs = sorted(sel)
        neg_all.extend(negs)
        neg_lens.append(len(negs))
    return (Tensor(jnp.asarray(np.asarray(neg_all, np.int32)
                               .reshape(-1, 1))),
            Tensor(jnp.asarray(np.asarray(neg_lens, np.int32))),
            Tensor(jnp.asarray(upd.astype(np.int32))))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, pixel_offset=True,
                             name=None):
    """Route RoIs to FPN levels by scale (reference:
    detection/distribute_fpn_proposals_op.h; python detection.py:3673):
    level = floor(log2(sqrt(area)/refer_scale + 1e-6)) + refer_level,
    clipped to [min_level, max_level]. Returns (multi_rois list,
    restore_index [R,1] [, multi_rois_num list])."""
    rois = np.asarray(unwrap(fpn_rois), np.float32)
    num_level = max_level - min_level + 1
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0], 0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0)
    invalid = (rois[:, 2] < rois[:, 0]) | (rois[:, 3] < rois[:, 1])
    area = np.where(invalid, 0.0, (w + off) * (h + off))
    scale = np.sqrt(area)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, multi_num, order = [], [], []
    lens = (np.asarray(unwrap(rois_num)).astype(np.int64).reshape(-1)
            if rois_num is not None else np.asarray([rois.shape[0]]))
    offs = np.concatenate([[0], np.cumsum(lens)])
    for k in range(num_level):
        sel_rows, per_im = [], []
        for b in range(len(lens)):
            seg = np.arange(offs[b], offs[b + 1])
            rows = seg[lvl[seg] == min_level + k]
            sel_rows.append(rows)
            per_im.append(len(rows))
        rows = np.concatenate(sel_rows) if sel_rows else \
            np.zeros(0, np.int64)
        multi.append(Tensor(jnp.asarray(rois[rows])))
        multi_num.append(Tensor(jnp.asarray(
            np.asarray(per_im, np.int32))))
        order.append(rows)
    concat_order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty(rois.shape[0], np.int64)
    restore[concat_order] = np.arange(rois.shape[0])
    restore_t = Tensor(jnp.asarray(restore.astype(np.int32)
                                   .reshape(-1, 1)))
    if rois_num is not None:
        return multi, restore_t, multi_num
    return multi, restore_t


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-level RoIs back, keep global top-k by score (reference:
    detection/collect_fpn_proposals_op.h; python detection.py:3871)."""
    rois = np.concatenate([np.asarray(unwrap(r), np.float32)
                           for r in multi_rois], axis=0)
    scores = np.concatenate([np.asarray(unwrap(s), np.float32).reshape(-1)
                             for s in multi_scores], axis=0)
    if rois_num_per_level is not None:
        lens = [np.asarray(unwrap(n_)).astype(np.int64).reshape(-1)
                for n_ in rois_num_per_level]
        num_im = len(lens[0])
        # image id per row, concatenated level-major
        img_of = np.concatenate([np.repeat(np.arange(num_im), l_)
                                 for l_ in lens])
    else:
        num_im = 1
        img_of = np.zeros(len(scores), np.int64)
    out_rows, out_nums = [], []
    for b in range(num_im):
        rows = np.nonzero(img_of == b)[0]
        order = rows[np.argsort(-scores[rows], kind="stable")]
        keep = order[:post_nms_top_n]
        # reference sorts the kept set back by (image) stable order? It
        # keeps score order within the image; we do the same.
        out_rows.append(keep)
        out_nums.append(len(keep))
    sel = np.concatenate(out_rows) if out_rows else np.zeros(0, np.int64)
    fpn_rois = Tensor(jnp.asarray(rois[sel]))
    if rois_num_per_level is not None:
        return fpn_rois, Tensor(jnp.asarray(
            np.asarray(out_nums, np.int32)))
    return fpn_rois


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0, name=None):
    """RetinaNet post-processing (reference:
    detection/retinanet_detection_output_op.cc; python
    detection.py:3106): per-level threshold + top-k, decode against
    anchors (+1 offsets, /im_scale, clip), then cross-level
    multiclass NMS. Returns (out [No,6], rois_num [N])."""
    bb = [np.asarray(unwrap(b), np.float32) for b in bboxes]
    sc = [np.asarray(unwrap(s), np.float32) for s in scores]
    an = [np.asarray(unwrap(a), np.float32) for a in anchors]
    info = np.asarray(unwrap(im_info), np.float32)
    n = bb[0].shape[0]
    outs, nums = [], []
    for im in range(n):
        im_h, im_w, im_scale = info[im]
        ih = round(float(im_h) / im_scale)
        iw = round(float(im_w) / im_scale)
        dets_per_class = {}
        for lv in range(len(bb)):
            s = sc[lv][im]                       # [M, C]
            m, c = s.shape
            flat = s.reshape(-1)
            cand = np.nonzero(flat > score_threshold)[0]
            if cand.size == 0:
                continue
            order = cand[np.argsort(-flat[cand], kind="stable")]
            order = order[:nms_top_k]
            aa = order // c
            cc = order % c
            anc_sel = an[lv][aa]
            del_sel = bb[lv][im][aa]
            props = _decode_proposals(anc_sel, del_sel, None,
                                      pixel_offset=True) / im_scale
            props[:, 0::2] = np.clip(props[:, 0::2], 0, iw - 1)
            props[:, 1::2] = np.clip(props[:, 1::2], 0, ih - 1)
            for k in range(len(order)):
                dets_per_class.setdefault(int(cc[k]), []).append(
                    np.concatenate([[flat[order[k]]], props[k]]))
        rows = []
        for cls, dets in dets_per_class.items():
            dets = np.asarray(dets, np.float32)
            kept = _nms_select(dets[:, 1:], dets[:, 0], -np.inf,
                               nms_threshold, -1, eta=nms_eta,
                               normalized=False)
            for i in kept:
                rows.append([float(cls + 1), dets[i, 0]] +
                            dets[i, 1:].tolist())
        rows.sort(key=lambda r: -r[1])
        if keep_top_k > -1:
            rows = rows[:keep_top_k]
        outs.extend(rows)
        nums.append(len(rows))
    out = np.asarray(outs, np.float32).reshape(-1, 6)
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(nums, np.int32))))


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, rois_lengths=None, gt_lengths=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, name=None):
    """Sample Fast-RCNN training RoIs + targets (reference:
    detection/generate_proposal_labels_op.cc; python detection.py:2596).

    Dense-ragged: rpn_rois packed [R,4] + rois_lengths [N]; gt_* packed
    + gt_lengths [N]. Returns (rois [S,4], labels_int32 [S,1],
    bbox_targets [S,4C], bbox_inside_weights [S,4C],
    bbox_outside_weights [S,4C], rois_num [N])."""
    rois = np.asarray(unwrap(rpn_rois), np.float32)
    gcls = np.asarray(unwrap(gt_classes)).astype(np.int64).reshape(-1)
    crowd = np.asarray(unwrap(is_crowd)).astype(np.int64).reshape(-1)
    gbox = np.asarray(unwrap(gt_boxes), np.float32)
    info = np.asarray(unwrap(im_info), np.float32)
    rlens = (np.asarray(unwrap(rois_lengths)).astype(np.int64).reshape(-1)
             if rois_lengths is not None
             else np.asarray([rois.shape[0]]))
    glens = (np.asarray(unwrap(gt_lengths)).astype(np.int64).reshape(-1)
             if gt_lengths is not None else np.asarray([gbox.shape[0]]))
    roffs = np.concatenate([[0], np.cumsum(rlens)])
    goffs = np.concatenate([[0], np.cumsum(glens)])
    rng = _sample_rng
    wts = np.asarray(bbox_reg_weights, np.float32)

    o_rois, o_lab, o_tgt, o_in, o_out, o_num = [], [], [], [], [], []
    for im in range(len(rlens)):
        r = rois[roffs[im]:roffs[im + 1]] / info[im, 2]   # orig scale
        g = gbox[goffs[im]:goffs[im + 1]]
        gc = gcls[goffs[im]:goffs[im + 1]]
        cr = crowd[goffs[im]:goffs[im + 1]]
        keep_gt = cr == 0
        g, gc = g[keep_gt], gc[keep_gt]
        cand = np.concatenate([r, g], axis=0) if g.size else r
        if len(g):
            iou = np.asarray(_iou_matrix(jnp.asarray(cand),
                                         jnp.asarray(g),
                                         normalized=False))
            mx = iou.max(axis=1)
            am = iou.argmax(axis=1)
        else:
            mx = np.zeros(len(cand), np.float32)
            am = np.zeros(len(cand), np.int64)
        fg = np.nonzero(mx >= fg_thresh)[0]
        bg = np.nonzero((mx < bg_thresh_hi) & (mx >= bg_thresh_lo))[0]
        num_fg = min(int(fg_fraction * batch_size_per_im), len(fg))
        fg = (rng.choice(fg, num_fg, replace=False) if use_random and
              len(fg) > num_fg else fg[:num_fg])
        num_bg = min(batch_size_per_im - len(fg), len(bg))
        bg = (rng.choice(bg, num_bg, replace=False) if use_random and
              len(bg) > num_bg else bg[:num_bg])
        sel = np.concatenate([fg, bg]).astype(np.int64)
        labels = np.concatenate([gc[am[fg]] if len(g) else
                                 np.zeros(len(fg), np.int64),
                                 np.zeros(len(bg), np.int64)])
        srois = cand[sel]
        ncls = 1 if is_cls_agnostic else class_nums
        tgt = np.zeros((len(sel), 4 * ncls), np.float32)
        inw = np.zeros_like(tgt)
        for k in range(len(fg)):
            if not len(g):
                break
            gt = g[am[fg[k]]]
            ex = srois[k]
            ew = ex[2] - ex[0] + 1
            eh = ex[3] - ex[1] + 1
            gw = gt[2] - gt[0] + 1
            gh = gt[3] - gt[1] + 1
            delta = np.asarray([
                ((gt[0] + gw / 2) - (ex[0] + ew / 2)) / ew,
                ((gt[1] + gh / 2) - (ex[1] + eh / 2)) / eh,
                np.log(gw / ew), np.log(gh / eh)]) / wts
            cls = 0 if is_cls_agnostic else int(labels[k])
            tgt[k, 4 * cls:4 * cls + 4] = delta
            inw[k, 4 * cls:4 * cls + 4] = 1.0
        o_rois.append(srois * info[im, 2])
        o_lab.append(labels)
        o_tgt.append(tgt)
        o_in.append(inw)
        o_out.append((inw > 0).astype(np.float32))
        o_num.append(len(sel))
    cat = lambda xs, d: (np.concatenate(xs, axis=0) if xs else  # noqa: E731
                         np.zeros((0, d), np.float32))
    ncls4 = 4 * (1 if is_cls_agnostic else class_nums)
    return (Tensor(jnp.asarray(cat(o_rois, 4))),
            Tensor(jnp.asarray(np.concatenate(o_lab).astype(np.int32)
                               .reshape(-1, 1))),
            Tensor(jnp.asarray(cat(o_tgt, ncls4))),
            Tensor(jnp.asarray(cat(o_in, ncls4))),
            Tensor(jnp.asarray(cat(o_out, ncls4))),
            Tensor(jnp.asarray(np.asarray(o_num, np.int32))))
