"""Vision transforms (reference: python/paddle/vision/transforms/).
Operate on numpy CHW float arrays (host-side, pre-device)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 2.0:
            arr = arr / 255.0
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        else:
            out_shape = tuple(self.size) + arr.shape[2:]
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        from ...core import rng

        if rng._numpy_generator.rand() < self.prob:
            return np.ascontiguousarray(np.flip(img, axis=-1))
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        from ...core import rng

        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)])
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = rng._numpy_generator.randint(0, h - th + 1)
        j = rng._numpy_generator.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[..., i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


from . import functional  # noqa: E402,F401
from .functional import (adjust_brightness, adjust_contrast,  # noqa: E402,F401
                         adjust_hue, adjust_saturation, center_crop, crop,
                         hflip, pad, rotate, to_grayscale, vflip)


class BaseTransform:
    """Keys-aware base (reference transforms.py:134); subclasses
    implement _apply_image (and optionally _apply_* for other keys)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        if self.keys is None:
            return self._apply_image(inputs)
        inputs = list(inputs)
        for i, k in enumerate(self.keys):
            fn = getattr(self, f"_apply_{k}", None)
            if fn is not None:
                inputs[i] = fn(inputs[i])
        return tuple(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        from ...core import rng

        if rng._numpy_generator.rand() < self.prob:
            return vflip(img)
        return img


class Transpose(BaseTransform):
    """HWC -> CHW (reference transforms.py:660)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        # number v -> [max(0, 1-v), 1+v]; 2-tuple passes through
        # (reference transforms.py _check_input contract)
        if isinstance(value, (tuple, list)):
            self.range = (float(value[0]), float(value[1]))
        else:
            v = float(value)
            self.range = None if v == 0 else (max(0.0, 1 - v), 1 + v)

    def _factor(self):
        from ...core import rng

        if self.range is None:
            return 1.0
        return float(rng._numpy_generator.uniform(*self.range))

    def _apply_image(self, img):
        return adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def __init__(self, value, keys=None):
        if not isinstance(value, (tuple, list)) and value < 0:
            raise ValueError("contrast value should be non-negative")
        super().__init__(value, keys)

    def _apply_image(self, img):
        return adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        return adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, (tuple, list)):
            self.range = (float(value[0]), float(value[1]))
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value should be in [0, 0.5]")
            self.range = None if value == 0 else (-float(value),
                                                  float(value))
        if self.range and not (-0.5 <= self.range[0]
                               <= self.range[1] <= 0.5):
            raise ValueError("hue range must lie in [-0.5, 0.5]")

    def _apply_image(self, img):
        from ...core import rng

        if self.range is None:
            return img
        return adjust_hue(img,
                          float(rng._numpy_generator.uniform(*self.range)))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference transforms.py:847)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        from ...core import rng

        order = rng._numpy_generator.permutation(len(self.ts))
        for i in order:
            img = self.ts[i]._apply_image(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            if degrees < 0:
                raise ValueError("degrees must be positive when scalar")
            self.degrees = (-degrees, degrees)
        else:
            self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        from ...core import rng

        angle = float(rng._numpy_generator.uniform(*self.degrees))
        return rotate(img, angle, self.interpolation, self.expand,
                      center=self.center, fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop resized to `size`
    (reference transforms.py:402)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math

        from ...core import rng

        arr = np.asarray(img)
        h, w = (arr.shape[-2:] if arr.ndim == 2
                or (arr.ndim == 3 and arr.shape[0] in (1, 3, 4))
                else arr.shape[:2])
        area = h * w
        gen = rng._numpy_generator
        for _ in range(10):
            target = area * gen.uniform(*self.scale)
            log_r = (math.log(self.ratio[0]), math.log(self.ratio[1]))
            ar = math.exp(gen.uniform(*log_r))
            tw = int(round(math.sqrt(target * ar)))
            th = int(round(math.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                top = gen.randint(0, h - th + 1)
                left = gen.randint(0, w - tw + 1)
                out = crop(arr, top, left, th, tw)
                return resize(out, self.size, self.interpolation)
        # fallback: center crop to the valid aspect (reference behavior)
        side = min(h, w)
        out = CenterCrop((side, side))(arr)
        return resize(out, self.size, self.interpolation)
