"""Vision transforms (reference: python/paddle/vision/transforms/).
Operate on numpy CHW float arrays (host-side, pre-device)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 2.0:
            arr = arr / 255.0
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        else:
            out_shape = tuple(self.size) + arr.shape[2:]
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        from ...core import rng

        if rng._numpy_generator.rand() < self.prob:
            return np.ascontiguousarray(np.flip(img, axis=-1))
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        from ...core import rng

        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)])
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = rng._numpy_generator.randint(0, h - th + 1)
        j = rng._numpy_generator.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[..., i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
