"""Functional image ops (reference: vision/transforms/functional.py,
dispatching to functional_{pil,cv2,tensor}.py). One numpy backend here:
images are CHW float arrays (the repo's dataset convention) or HWC/HW
arrays — channel order is inferred the way ToTensor does.
"""
from __future__ import annotations

import numpy as np

__all__ = ["to_tensor", "resize", "pad", "crop", "center_crop", "hflip",
           "vflip", "adjust_brightness", "adjust_contrast",
           "adjust_saturation", "adjust_hue", "rotate", "to_grayscale",
           "normalize"]


def _is_chw(arr):
    return arr.ndim == 3 and arr.shape[0] in (1, 3, 4)


def to_tensor(pic, data_format="CHW"):
    from . import ToTensor

    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from . import Normalize

    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    from . import Resize

    return Resize(size, interpolation)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    """reference functional.py:149 — padding int | (pad_lr, pad_tb) |
    (left, top, right, bottom)."""
    arr = np.asarray(img)
    if isinstance(padding, int):
        l = t = r = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    spec = [(0, 0)] * (arr.ndim - 2) + [(t, b), (l, r)] if _is_chw(arr) \
        or arr.ndim == 2 else [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, spec, mode=mode, **kw)


def _hw_slice(arr, top, left, height, width):
    if _is_chw(arr) or arr.ndim == 2:
        return arr[..., top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


def crop(img, top, left, height, width):
    return _hw_slice(np.asarray(img), top, left, height, width)


def center_crop(img, output_size):
    from . import CenterCrop

    return CenterCrop(output_size)(img)


def hflip(img):
    arr = np.asarray(img)
    ax = -1 if (_is_chw(arr) or arr.ndim == 2) else 1
    return np.ascontiguousarray(np.flip(arr, axis=ax))


def vflip(img):
    arr = np.asarray(img)
    ax = -2 if (_is_chw(arr) or arr.ndim == 2) else 0
    return np.ascontiguousarray(np.flip(arr, axis=ax))


def _blend(a, b, factor):
    out = a.astype(np.float32) * factor + b.astype(np.float32) * (1 - factor)
    return out.astype(np.float32)


def _gray(arr):
    """Luma (ITU-R 601, the reference's conversion) along the channel
    axis; arr CHW or HWC float. An HW image is already grayscale."""
    if arr.ndim == 2:
        return arr.astype(np.float32)
    w = np.asarray([0.299, 0.587, 0.114], np.float32)
    if _is_chw(arr):
        return np.tensordot(w, arr.astype(np.float32)[:3], 1)
    return np.tensordot(arr.astype(np.float32)[..., :3], w, 1)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img, np.float32)
    return _blend(arr, np.zeros_like(arr), brightness_factor)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = _gray(arr).mean()
    return _blend(arr, np.full_like(arr, mean), contrast_factor)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img, np.float32)
    g = _gray(arr)
    g = g[None] if _is_chw(arr) else g[..., None]
    return _blend(arr, np.broadcast_to(g, arr.shape), saturation_factor)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor ∈ [-0.5, 0.5] of a full HSV turn
    (reference functional.py adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img, np.float32)
    chw = _is_chw(arr)
    rgb = arr if not chw else arr.transpose(1, 2, 0)
    scale = 255.0 if rgb.max() > 2.0 else 1.0
    rgb = rgb / scale
    mx, mn = rgb.max(-1), rgb.min(-1)
    diff = mx - mn
    safe = np.where(diff == 0, 1.0, diff)
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(mx == r, (g - b) / safe % 6,
                 np.where(mx == g, (b - r) / safe + 2, (r - g) / safe + 4))
    h = np.where(diff == 0, 0.0, h) / 6.0
    s = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = mx * (1 - s)
    q = mx * (1 - s * f)
    t = mx * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    out = np.select(
        [(i == k)[..., None] for k in range(6)],
        [np.stack([mx, t, p], -1), np.stack([q, mx, p], -1),
         np.stack([p, mx, t], -1), np.stack([p, q, mx], -1),
         np.stack([t, p, mx], -1), np.stack([mx, p, q], -1)])
    out = (out * scale).astype(np.float32)
    return out.transpose(2, 0, 1) if chw else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """reference functional.py rotate (angle in degrees CCW; optional
    rotation origin ``center`` as (x, y), incompatible with expand —
    same constraint as the reference, whose expand assumes a center
    rotation)."""
    from scipy import ndimage

    arr = np.asarray(img, np.float32)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}[interpolation]
    if center is not None:
        if expand:
            raise ValueError("rotate: center and expand are mutually "
                             "exclusive (reference semantics)")
        th = np.deg2rad(angle)
        # inverse map for affine_transform: out -> in, about (cy, cx)
        rot = np.array([[np.cos(th), np.sin(th)],
                        [-np.sin(th), np.cos(th)]], np.float64)
        cx, cy = center
        c = np.array([cy, cx], np.float64)
        off = c - rot @ c

        def one(plane):
            return ndimage.affine_transform(
                plane, rot, offset=off, order=order, cval=fill)

        if arr.ndim == 2:
            return one(arr).astype(np.float32)
        if _is_chw(arr):
            return np.stack([one(p) for p in arr]).astype(np.float32)
        return np.stack([one(arr[..., i]) for i in
                         range(arr.shape[-1])], -1).astype(np.float32)
    axes = (-2, -1) if (_is_chw(arr) or arr.ndim == 2) else (0, 1)
    return ndimage.rotate(arr, angle, axes=(axes[1], axes[0]),
                          reshape=expand, order=order, cval=fill) \
        .astype(np.float32)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    g = _gray(arr)
    if _is_chw(arr):
        g = np.repeat(g[None], num_output_channels, 0)
    else:
        g = np.repeat(g[..., None], num_output_channels, -1)
    return g.astype(np.float32)
