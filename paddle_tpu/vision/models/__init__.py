"""Model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152)
