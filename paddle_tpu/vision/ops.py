"""Detection / region ops: roi_align, psroi_pool, prior_box, yolo_box.

TPU-native equivalents of the reference detection op family
(reference: paddle/fluid/operators/roi_align_op.cc,
operators/detection/prior_box_op.cc, operators/detection/yolo_box_op.cc,
python API fluid/layers/nn.py:6964 roi_align, fluid/layers/detection.py:1134
yolo_box, :1764 prior_box). All ops are vectorized gathers/elementwise over
static shapes — jittable, and roi_align/psroi_pool differentiable w.r.t. the
feature map via jax AD (the reference hand-writes the scatter-add backward).

Deviation (documented): the reference's ``sampling_ratio=-1`` picks a
per-RoI adaptive sample count (ceil(roi_size/pooled_size)) — a data-
dependent shape that cannot live under jit. Here ``sampling_ratio<=0``
falls back to a fixed 2x2 sampling grid per bin (the detectron default).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor._helper import apply, unwrap, wrap

__all__ = ["roi_align", "psroi_pool", "prior_box", "yolo_box"]


def _bilinear_gather(xf, b, gy, gx, h, w):
    """Sample xf [N, C, H*W] at continuous (gy, gx) with batch index b.
    Reference bilinear_interpolate semantics (roi_align_op.h): samples
    outside [-1, size] are zero; in-range coords are clamped to
    [0, size-1] (the high corner collapses with weight 0 at the far
    edge). b broadcasts against gy/gx; returns [..., C]."""
    valid = ((gy >= -1.0) & (gy <= h) & (gx >= -1.0) & (gx <= w))
    gy = jnp.clip(gy, 0.0, h - 1.0)
    gx = jnp.clip(gx, 0.0, w - 1.0)
    y0 = jnp.floor(gy)
    x0 = jnp.floor(gx)
    wy = gy - y0
    wx = gx - x0
    y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)

    def at(iy, ix):
        idx = jnp.minimum(iy, h - 1) * w + jnp.minimum(ix, w - 1)
        return xf[b, :, idx]                      # [..., C]

    out = (at(y0i, x0i) * ((1 - wx) * (1 - wy))[..., None]
           + at(y0i, x0i + 1) * (wx * (1 - wy))[..., None]
           + at(y0i + 1, x0i) * ((1 - wx) * wy)[..., None]
           + at(y0i + 1, x0i + 1) * (wx * wy)[..., None])
    return out * valid[..., None].astype(xf.dtype)


def _roi_batch_index(rois_num, num_rois):
    """rois_num [N] (rois per image) -> batch index per roi [R]."""
    reps = np.asarray(rois_num).astype(np.int64).reshape(-1)
    return jnp.asarray(np.repeat(np.arange(len(reps)), reps)
                       .astype(np.int32))


def _sample_coords(rois, pooled_h, pooled_w, spatial_scale, ratio,
                   aligned=False):
    """Per-bin sampling point coordinates [R, PH, PW, s, s] (y and x)."""
    x1 = rois[:, 0] * spatial_scale
    y1 = rois[:, 1] * spatial_scale
    x2 = rois[:, 2] * spatial_scale
    y2 = rois[:, 3] * spatial_scale
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:
        # legacy semantics clamp degenerate rois to 1px; aligned mode
        # (detectron2) keeps the true size
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_w = roi_w / pooled_w
    bin_h = roi_h / pooled_h
    s = ratio
    ph = jnp.arange(pooled_h, dtype=rois.dtype)
    pw = jnp.arange(pooled_w, dtype=rois.dtype)
    iy = jnp.arange(s, dtype=rois.dtype)
    # y = y1 + ph*bin_h + (iy+0.5)*bin_h/s  (reference roi_align_op.h)
    gy = (y1[:, None, None] + ph[None, :, None] * bin_h[:, None, None]
          + (iy[None, None, :] + 0.5) * bin_h[:, None, None] / s)
    gx = (x1[:, None, None] + pw[None, :, None] * bin_w[:, None, None]
          + (iy[None, None, :] + 0.5) * bin_w[:, None, None] / s)
    # [R, PH, 1, s, 1] and [R, 1, PW, 1, s]
    return gy[:, :, None, :, None], gx[:, None, :, None, :]


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=False, name=None,
              # legacy fluid aliases (fluid/layers/nn.py:6964)
              pooled_height=None, pooled_width=None, rois_num=None):
    """RoI Align (Mask R-CNN): average of bilinear samples per output bin.

    x [N, C, H, W]; boxes [R, 4] as [x1, y1, x2, y2]; boxes_num [N] rois
    per image. Returns [R, C, ph, pw]. ``aligned=True`` shifts sampling by
    -0.5 (the detectron2 convention; reference gained it post-2.0)."""
    if pooled_height is not None or pooled_width is not None:
        ph, pw = int(pooled_height or 1), int(pooled_width or 1)
    elif isinstance(output_size, (tuple, list)):
        ph, pw = int(output_size[0]), int(output_size[1])
    else:
        ph = pw = int(output_size)
    if rois_num is not None and boxes_num is None:
        boxes_num = rois_num
    ratio = int(sampling_ratio) if int(sampling_ratio) > 0 else 2

    boxes_v = unwrap(boxes)
    num_rois = int(boxes_v.shape[0])
    if boxes_num is None:
        b_idx = jnp.zeros((num_rois,), jnp.int32)
    else:
        bn = boxes_num.numpy() if hasattr(boxes_num, "numpy") else boxes_num
        b_idx = _roi_batch_index(bn, num_rois)

    def f(xv, rois):
        n, c, h, w = xv.shape
        rois = rois.astype(jnp.float32)
        if aligned:
            rois = rois - 0.5 / spatial_scale
        gy, gx = _sample_coords(rois, ph, pw, spatial_scale, ratio,
                                aligned=aligned)
        gy = jnp.broadcast_to(gy, (num_rois, ph, pw, ratio, ratio))
        gx = jnp.broadcast_to(gx, (num_rois, ph, pw, ratio, ratio))
        xf = xv.reshape(n, c, h * w)
        b = b_idx[:, None, None, None, None]
        vals = _bilinear_gather(xf, jnp.broadcast_to(b, gy.shape),
                                gy, gx, h, w)
        pooled = jnp.mean(vals, axis=(3, 4))          # [R, PH, PW, C]
        return jnp.transpose(pooled, (0, 3, 1, 2))

    return apply(f, x, boxes, name="roi_align")


def psroi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN): output channel (c, i, j)
    average-pools input channel c*ph*pw + i*pw + j over bin (i, j)
    (reference: operators/detection/... psroi_pool_op.cc)."""
    if isinstance(output_size, (tuple, list)):
        ph, pw = int(output_size[0]), int(output_size[1])
    else:
        ph = pw = int(output_size)
    boxes_v = unwrap(boxes)
    num_rois = int(boxes_v.shape[0])
    if boxes_num is None:
        b_idx = jnp.zeros((num_rois,), jnp.int32)
    else:
        bn = boxes_num.numpy() if hasattr(boxes_num, "numpy") else boxes_num
        b_idx = _roi_batch_index(bn, num_rois)
    ratio = 2   # fixed sampling grid per bin (see module docstring)

    def f(xv, rois):
        n, c, h, w = xv.shape
        out_c = c // (ph * pw)
        rois = rois.astype(jnp.float32)
        gy, gx = _sample_coords(rois, ph, pw, spatial_scale, ratio)
        gy = jnp.broadcast_to(gy, (num_rois, ph, pw, ratio, ratio))
        gx = jnp.broadcast_to(gx, (num_rois, ph, pw, ratio, ratio))
        xf = xv.reshape(n, c, h * w)
        b = b_idx[:, None, None, None, None]
        vals = _bilinear_gather(xf, jnp.broadcast_to(b, gy.shape),
                                gy, gx, h, w)          # [R,PH,PW,s,s,C]
        pooled = jnp.mean(vals, axis=(3, 4))           # [R, PH, PW, C]
        # position-sensitive channel select: out[r, k, i, j] uses input
        # channel k*ph*pw + i*pw + j
        pooled = jnp.transpose(pooled, (0, 3, 1, 2))   # [R, C, PH, PW]
        pooled = pooled.reshape(num_rois, out_c, ph, pw, ph, pw)
        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        return pooled[:, :, ii[:, None], jj[None, :], ii[:, None],
                      jj[None, :]]

    return apply(f, x, boxes, name="psroi_pool")


def _expand_aspect_ratios(aspect_ratios, flip):
    """Reference ExpandAspectRatios (prior_box_op.h): start from [1.0],
    append each new ratio (and its flip)."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - e) < 1e-6 for e in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes for one feature map.

    input [N, C, H, W] (feature map), image [N, C, imgH, imgW]. Returns
    (boxes [H, W, P, 4] in normalized [x1, y1, x2, y2], variances same
    shape). Reference: operators/detection/prior_box_op.{cc,h},
    fluid/layers/detection.py:1764."""
    feat = unwrap(input)
    img = unwrap(image)
    fh, fw = int(feat.shape[2]), int(feat.shape[3])
    ih, iw = int(img.shape[2]), int(img.shape[3])
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] \
        if max_sizes else []
    ars = _expand_aspect_ratios(list(np.atleast_1d(aspect_ratios)), flip)
    step_w = float(steps[0]) if steps and steps[0] > 0 else iw / fw
    step_h = float(steps[1]) if steps and steps[1] > 0 else ih / fh

    boxes = []     # per-position list of [4]
    for k, ms in enumerate(min_sizes):
        prio = []
        for ar in ars:
            bw = ms * math.sqrt(ar) / 2.0
            bh = ms / math.sqrt(ar) / 2.0
            prio.append((bw, bh))
        sq = []
        if max_sizes:
            s = math.sqrt(ms * max_sizes[k])
            sq.append((s / 2.0, s / 2.0))
        if min_max_aspect_ratios_order:
            # min box first, then the sqrt(min*max) box, then ratios
            order = [prio[0]] + sq + prio[1:]
        else:
            order = prio + sq
        boxes.extend(order)
    p = len(boxes)

    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    gcx, gcy = np.meshgrid(cx, cy)                     # [H, W]
    half = np.asarray(boxes, np.float32)               # [P, 2]
    out = np.empty((fh, fw, p, 4), np.float32)
    out[..., 0] = (gcx[:, :, None] - half[None, None, :, 0]) / iw
    out[..., 1] = (gcy[:, :, None] - half[None, None, :, 1]) / ih
    out[..., 2] = (gcx[:, :, None] + half[None, None, :, 0]) / iw
    out[..., 3] = (gcy[:, :, None] + half[None, None, :, 1]) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """Decode YOLOv3 head output into boxes + scores.

    x [N, A*(5+classes), H, W]; img_size [N, 2] as (h, w). Returns
    (boxes [N, H*W*A, 4] in x1y1x2y2 image coords,
     scores [N, H*W*A, class_num]). Boxes whose objectness is below
    conf_thresh are zeroed. Reference: operators/detection/yolo_box_op.h,
    fluid/layers/detection.py:1134."""
    anchors = [int(a) for a in anchors]
    na = len(anchors) // 2
    cn = int(class_num)

    def f(xv, imgs):
        n, _, h, w = xv.shape
        dt = jnp.float32
        xv = xv.astype(dt)
        v = xv.reshape(n, na, 5 + cn, h, w)
        tx, ty, tw, th = v[:, :, 0], v[:, :, 1], v[:, :, 2], v[:, :, 3]
        obj = 1.0 / (1.0 + jnp.exp(-v[:, :, 4]))
        cls = 1.0 / (1.0 + jnp.exp(-v[:, :, 5:]))      # [N, A, cn, H, W]

        gx = jnp.arange(w, dtype=dt)[None, None, None, :]
        gy = jnp.arange(h, dtype=dt)[None, None, :, None]
        sx = 1.0 / (1.0 + jnp.exp(-tx)) * scale_x_y - 0.5 * (scale_x_y - 1)
        sy = 1.0 / (1.0 + jnp.exp(-ty)) * scale_x_y - 0.5 * (scale_x_y - 1)
        img_h = imgs[:, 0].astype(dt)[:, None, None, None]
        img_w = imgs[:, 1].astype(dt)[:, None, None, None]
        # anchor sizes are in input-image pixels; input size = grid *
        # downsample
        in_w = w * downsample_ratio
        in_h = h * downsample_ratio
        aw = jnp.asarray(anchors[0::2], dt)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], dt)[None, :, None, None]
        cx = (sx + gx) / w * img_w                     # center, image px
        cy = (sy + gy) / h * img_h
        bw = jnp.exp(tw) * aw / in_w * img_w
        bh = jnp.exp(th) * ah / in_h * img_h
        x1 = cx - bw / 2.0
        y1 = cy - bh / 2.0
        x2 = cx + bw / 2.0
        y2 = cy + bh / 2.0
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, img_w - 1)
            y1 = jnp.clip(y1, 0.0, img_h - 1)
            x2 = jnp.clip(x2, 0.0, img_w - 1)
            y2 = jnp.clip(y2, 0.0, img_h - 1)
        keep = (obj >= conf_thresh).astype(dt)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
        scores = cls * (obj * keep)[:, :, None]
        # reference layout is anchor-major: box row a*H*W + i*W + j
        # (yolo_box_op.h box_idx = j*stride + k*w + l, stride = H*W)
        boxes = boxes.reshape(n, -1, 4)                # [N, A, H, W, 4]
        scores = jnp.transpose(scores, (0, 1, 3, 4, 2)).reshape(n, -1, cn)
        return boxes, scores

    return apply(f, x, img_size, name="yolo_box")


from ..nn.functional.vision import deform_conv2d  # noqa: F401,E402
from .. import nn as _nn  # noqa: E402

__all__ += ["deform_conv2d", "DeformConv2D"]


class DeformConv2D(_nn.Layer):
    """Deformable-conv layer (reference: python/paddle/vision/ops.py
    DeformConv2D over deformable_conv_op.cc). Offsets/mask come from the
    caller (usually a small plain conv branch), per the reference API."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation, groups=groups)
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *ks],
            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._attrs)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: operators/detection/yolov3_loss_op.{cc,h},
    python/paddle/vision/ops.py yolo_loss). Per image: sigmoid-CE for
    (x, y), L1 for (w, h) — scaled by (2 − gw·gh)·score — sigmoid-CE
    objectness (ignored where a prediction's best-gt IoU exceeds
    ``ignore_thresh``), sigmoid-CE classification with optional label
    smoothing. The reference's quadruple CPU loop becomes one decoded
    [N,S,H,W]×[N,B] IoU tensor + scatter/gather — no scalar loops, and
    jax AD replaces the hand-written grad kernel. Returns [N]."""
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(m) for m in anchor_mask]
    an_num = len(anchors) // 2
    S = len(anchor_mask)
    C = int(class_num)
    sxy = float(scale_x_y)
    bias = -0.5 * (sxy - 1.0)

    def sce(logit, label):
        return jnp.maximum(logit, 0.0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xv, gb, gl, *rest):
        gs = rest[0] if rest else None
        n, _, h, w = xv.shape
        b = gb.shape[1]
        input_size = downsample_ratio * h
        v = xv.reshape(n, S, 5 + C, h, w)
        gvalid = (gb[..., 2] > 1e-6) & (gb[..., 3] > 1e-6)      # [N, B]
        score = jnp.ones((n, b), xv.dtype) if gs is None \
            else gs.astype(xv.dtype)

        # ---- objectness ignore: decoded pred vs every gt ----------------
        aw = jnp.asarray([anchors[2 * m] for m in anchor_mask], xv.dtype)
        ah = jnp.asarray([anchors[2 * m + 1] for m in anchor_mask],
                         xv.dtype)
        cx = jnp.arange(w, dtype=xv.dtype)[None, None, None, :]
        cy = jnp.arange(h, dtype=xv.dtype)[None, None, :, None]
        px = (cx + jax.nn.sigmoid(v[:, :, 0]) * sxy + bias) / w
        py = (cy + jax.nn.sigmoid(v[:, :, 1]) * sxy + bias) / h
        pw = jnp.exp(v[:, :, 2]) * aw[None, :, None, None] / input_size
        ph = jnp.exp(v[:, :, 3]) * ah[None, :, None, None] / input_size

        def overlap(c1, w1, c2, w2):
            left = jnp.maximum(c1 - w1 / 2, c2 - w2 / 2)
            right = jnp.minimum(c1 + w1 / 2, c2 + w2 / 2)
            return right - left

        gbx = gb[:, None, None, None, :, 0]          # [N,1,1,1,B]
        gby = gb[:, None, None, None, :, 1]
        gbw = gb[:, None, None, None, :, 2]
        gbh = gb[:, None, None, None, :, 3]
        ow = overlap(px[..., None], pw[..., None], gbx, gbw)
        oh = overlap(py[..., None], ph[..., None], gby, gbh)
        inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
        union = pw[..., None] * ph[..., None] + gbw * gbh - inter
        iou = jnp.where(gvalid[:, None, None, None, :],
                        inter / jnp.maximum(union, 1e-10), 0.0)
        ignore = jnp.max(iou, -1) > ignore_thresh     # [N,S,H,W]

        # ---- per-gt best-anchor matching --------------------------------
        aw_all = jnp.asarray(anchors[0::2], xv.dtype) / input_size
        ah_all = jnp.asarray(anchors[1::2], xv.dtype) / input_size
        ow = jnp.minimum(gb[..., 2:3] / 2, aw_all / 2) \
            - jnp.maximum(-gb[..., 2:3] / 2, -aw_all / 2)
        oh = jnp.minimum(gb[..., 3:4] / 2, ah_all / 2) \
            - jnp.maximum(-gb[..., 3:4] / 2, -ah_all / 2)
        inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
        union = gb[..., 2:3] * gb[..., 3:4] + aw_all * ah_all - inter
        an_iou = inter / jnp.maximum(union, 1e-10)    # [N,B,an_num]
        best_n = jnp.argmax(an_iou, -1)               # [N,B]
        m2i = -jnp.ones((an_num,), jnp.int32)
        m2i = m2i.at[jnp.asarray(anchor_mask)].set(
            jnp.arange(S, dtype=jnp.int32))
        mask_idx = m2i[best_n]                        # [N,B], -1 unmasked
        matched = gvalid & (mask_idx >= 0)

        gi = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)

        # gather the matched cell's 5+C channels: [N,B,5+C]
        sidx = jnp.maximum(mask_idx, 0)
        bidx = jnp.arange(n)[:, None]
        cell = v[bidx, sidx, :, gj, gi]

        aw_b = jnp.asarray(anchors[0::2], xv.dtype)[best_n]
        ah_b = jnp.asarray(anchors[1::2], xv.dtype)[best_n]
        tx = gb[..., 0] * w - gi.astype(xv.dtype)
        ty = gb[..., 1] * h - gj.astype(xv.dtype)
        safe_w = jnp.where(matched, gb[..., 2], 1.0)
        safe_h = jnp.where(matched, gb[..., 3], 1.0)
        tw = jnp.log(safe_w * input_size / aw_b)
        th = jnp.log(safe_h * input_size / ah_b)
        bscale = (2.0 - gb[..., 2] * gb[..., 3]) * score
        box = sce(cell[..., 0], tx) + sce(cell[..., 1], ty) \
            + jnp.abs(cell[..., 2] - tw) + jnp.abs(cell[..., 3] - th)
        box_loss = jnp.sum(jnp.where(matched, box * bscale, 0.0), -1)

        if use_label_smooth:
            sm = min(1.0 / C, 1.0 / 40)
            pos, neg = 1.0 - sm, sm
        else:
            pos, neg = 1.0, 0.0
        onehot = jax.nn.one_hot(gl, C, dtype=xv.dtype)
        labels = onehot * pos + (1 - onehot) * neg    # [N,B,C]
        cls = jnp.sum(sce(cell[..., 5:], labels), -1) * score
        cls_loss = jnp.sum(jnp.where(matched, cls, 0.0), -1)

        # ---- objectness: assignment scatters score over the ignore base.
        # Reference branch structure (yolov3_loss_op.h CalcObjnessLoss):
        # obj > 1e-5 → positive (weight = mixup score); obj > -0.5 →
        # negative sce(conf, 0) — an ASSIGNED cell with score ≈ 0
        # (mixup) still takes the negative branch, and assignment
        # overrides an earlier ignore (-1).
        assigned = jnp.zeros((n, S, h, w), jnp.bool_)
        pos_score = jnp.zeros((n, S, h, w), xv.dtype)
        assigned = assigned.at[bidx, sidx, gj, gi].max(matched)
        # two gts colliding on one (cell, anchor): the reference's
        # sequential loop is last-write-wins on the score. Scatter-max
        # of each gt's ORDER first, then only the winning gt writes its
        # score (deterministic, no duplicate-scatter ambiguity).
        order = jnp.where(matched,
                          jnp.arange(1, b + 1, dtype=jnp.int32)[None, :],
                          0)
        last = jnp.zeros((n, S, h, w), jnp.int32) \
            .at[bidx, sidx, gj, gi].max(order)
        is_last = matched & (last[bidx, sidx, gj, gi] == order)
        pos_score = pos_score.at[bidx, sidx, gj, gi].max(
            jnp.where(is_last, score, 0.0))
        conf = v[:, :, 4]
        pos = assigned & (pos_score > 1e-5)
        neg = ~pos & (assigned | ~ignore)
        obj_loss = jnp.where(
            pos, sce(conf, 1.0) * pos_score,
            jnp.where(neg, sce(conf, 0.0), 0.0))
        obj_loss = jnp.sum(obj_loss.reshape(n, -1), -1)

        return box_loss + cls_loss + obj_loss

    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None
                                    else [])
    return apply(f, *args, name="yolo_loss")


__all__ += ["yolo_loss"]
