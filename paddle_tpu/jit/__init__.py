"""paddle.jit equivalent."""
from .api import (InputSpec, StaticLayer, TracedLayer, load, save,  # noqa: F401
                  to_static)


def not_to_static(fn):
    return fn


def enable_to_static(flag: bool):
    return None
