"""jit: compiled execution of Layers and functions.

TPU-native analogue of paddle.jit.to_static / TracedLayer / jit.save
(reference: python/paddle/fluid/dygraph/jit.py, dygraph_to_static/
program_translator.py:756, imperative/jit/ ProgramDescTracer). Here
"static graph" == jaxpr/StableHLO: we trace forward once per input shape
and hand it to XLA, while keeping the result differentiable by registering
the whole compiled forward as ONE node on the eager tape.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional

import jax
import numpy as np

from ..autograd import tape
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from ..static.functional import functional_call, state_tensors
from ..static.input_spec import InputSpec  # noqa: F401 (re-export)


class StaticLayer:
    """Compiled wrapper around a Layer (or plain function)."""

    def __init__(self, target, input_spec=None):
        self._target = target
        self._input_spec = input_spec
        self._is_layer = isinstance(target, Layer)
        self._compiled = {}
        # dy2static AST pre-pass (reference: dygraph_to_static
        # program_translator.py convert_to_static): tensor-dependent
        # if/while in the target's forward become cond/while_loop so
        # they stage under tracing. No-op when the source has no
        # control flow or is unavailable. The user's layer is NOT
        # mutated: the converted forward is swapped in only for the
        # duration of each traced call (_swap_forward).
        import inspect as _inspect
        import types as _types

        from .dy2static import convert_to_static

        self._converted_forward = None
        if self._is_layer:
            conv = convert_to_static(type(target).forward)
            if conv is not None:
                self._converted_forward = _types.MethodType(conv, target)
        elif _inspect.ismethod(target):
            conv = convert_to_static(target.__func__)
            if conv is not None:
                self._target = _types.MethodType(conv, target.__self__)
        else:
            conv = convert_to_static(target)
            if conv is not None:
                self._target = conv
        if self._is_layer:
            self._jit_fn = jax.jit(self._pure_forward,
                                   static_argnames=("training",))

    # pure function traced by XLA
    def _pure_forward(self, param_vals, buffer_vals, key, arg_vals,
                      training=False):
        from .dy2static import swapped_forward

        with swapped_forward(self._target, self._converted_forward):
            out, new_buf = functional_call(self._target, param_vals,
                                           buffer_vals, arg_vals,
                                           training=training, rng_key=key)
        return out, new_buf

    def __call__(self, *args):
        if not self._is_layer:
            fn = self._target
            vals = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
            if not hasattr(self, "_fn_jit"):
                def raw(*vs):
                    outs = fn(*[Tensor(v) for v in vs])
                    return jax.tree_util.tree_map(
                        lambda x: x._value if isinstance(x, Tensor) else x,
                        outs, is_leaf=lambda x: isinstance(x, Tensor))

                self._fn_jit = raw
            return tape.apply(self._fn_jit, *vals, name="jit_fn")

        from ..core import rng

        layer = self._target
        pn, pt, bn, bt = state_tensors(layer)
        arg_tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        key = rng.next_key()
        training = layer.training

        def run(*flat):
            n_p, n_b, n_a = len(pt), len(bt), len(arg_tensors)
            p_vals = flat[:n_p]
            b_vals = flat[n_p:n_p + n_b]
            a_vals = flat[n_p + n_b:n_p + n_b + n_a]
            out, new_buf = self._jit_fn(list(p_vals), list(b_vals), key,
                                        list(a_vals), training=training)
            return out

        out = tape.apply(run, *(pt + bt + arg_tensors), name="jit_layer")
        return out

    # paddle API surface
    @property
    def forward(self):
        return self.__call__

    def state_dict(self):
        return self._target.state_dict()

    def parameters(self):
        return self._target.parameters() if self._is_layer else []


def to_static(layer=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static — decorator or direct call."""
    def wrap(t):
        return StaticLayer(t, input_spec)

    if layer is None:
        return wrap
    return wrap(layer)


def save(layer, path, input_spec=None, batch_buckets=None,
         batched_inputs=None, **config):
    """paddle.jit.save equivalent (reference: fluid/dygraph/jit.py save).

    Persists:
      - ``path.pdparams``   — pickled numpy state_dict
      - ``path.pdmodel.bin``— jax.export portable artifact of the forward
        (when input_spec given): a versioned, EXECUTABLE serialized
        program — the ProgramDesc analogue. ``paddle_tpu.inference``'s
        Predictor and ``jit.load`` run it without the Python class.
      - ``path.pdmodel``    — StableHLO text of the same forward (human-
        inspectable, like the reference's saved ProgramDesc proto text)
      - ``path.pdmeta``     — class/param-name/spec metadata
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    target = layer._target if isinstance(layer, StaticLayer) else layer
    state = {k: np.asarray(v._value)
             for k, v in target.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"class": type(target).__name__}
    if input_spec:
        from ..static.functional import functional_call, state_tensors

        pn, pt, bn, bt = state_tensors(target)
        meta["param_names"] = list(pn)
        meta["buffer_names"] = list(bn)
        meta["input_specs"] = [(tuple(s.shape), str(np.dtype(s.dtype)))
                               for s in input_spec]
        meta["input_names"] = [getattr(s, "name", None) or f"x{i}"
                               for i, s in enumerate(input_spec)]
        specs = [jax.ShapeDtypeStruct(tuple(s.shape), np.dtype(s.dtype))
                 for s in input_spec]

        # dy2static: export must trace the CONVERTED forward too — a
        # control-flow model that runs via to_static would otherwise
        # fail export with a swallowed TracerBoolConversionError
        import types as _types

        from .dy2static import convert_to_static, swapped_forward

        if isinstance(layer, StaticLayer) and \
                layer._converted_forward is not None:
            _conv_bound = layer._converted_forward
        else:
            _conv = convert_to_static(type(target).forward)
            _conv_bound = _types.MethodType(_conv, target) \
                if _conv is not None else None

        def pure(p_vals, b_vals, *a_vals):
            with swapped_forward(target, _conv_bound):
                out, _ = functional_call(target, p_vals, b_vals, a_vals,
                                         training=False)
            return out

        p_specs = [jax.ShapeDtypeStruct(p._value.shape, p._value.dtype)
                   for p in pt]
        b_specs = [jax.ShapeDtypeStruct(b._value.shape, b._value.dtype)
                   for b in bt]
        try:
            from jax import export as jax_export

            exported = jax_export.export(jax.jit(pure))(
                p_specs, b_specs, *specs)
            with open(path + ".pdmodel.bin", "wb") as f:
                f.write(exported.serialize())
            meta["exported"] = True
            # the batch dim is inputs[0]'s leading dim; only specs
            # sharing it are re-bucketed/padded — unbatched aux inputs
            # (lookup tables, per-class priors) keep their shape. The
            # batched-INPUT indices and batched-OUTPUT positions are
            # recorded in meta so the serving side pads/slices from the
            # save-time truth, not runtime shape guessing (eval_shape at
            # two batch sizes — abstract, no compile). Recorded for
            # every export: the Predictor pads up to the BASE batch even
            # when no buckets were requested.
            base_b = tuple(input_spec[0].shape)[0] \
                if len(input_spec[0].shape) else None
            if batched_inputs is not None:
                # explicit caller truth (like batch_buckets) — an
                # unbatched aux input whose leading dim happens to equal
                # the batch (e.g. a [4,K] table at batch 4) cannot be
                # told apart by shape alone.
                batched_in = sorted(int(i) for i in batched_inputs)
            else:
                batched_in = [i for i, s in enumerate(input_spec)
                              if len(s.shape) and s.shape[0] == base_b]
                if base_b is not None and len(batched_in) > 1:
                    # Sensitivity check: candidate i is truly batched iff
                    # holding it fixed while the other candidates grow
                    # breaks shape agreement (eval_shape — abstract, no
                    # compile). An aux input independent of the batch
                    # passes and is dropped from the batched set.
                    confirmed = []
                    for i in batched_in:
                        others = [jax.ShapeDtypeStruct(
                            ((base_b + 1,) + tuple(s.shape[1:]))
                            if (j in batched_in and j != i)
                            else tuple(s.shape), np.dtype(s.dtype))
                            for j, s in enumerate(input_spec)]
                        try:
                            jax.eval_shape(pure, p_specs, b_specs, *others)
                            # fn is insensitive to i staying at base_b
                            # while the batch grows → i is not batched
                        except Exception:
                            confirmed.append(i)
                    # keep shape-heuristic fallback if the check degenerates
                    # (e.g. fn broadcasts everything and nothing errors)
                    if confirmed:
                        batched_in = confirmed

            def specs_at(n):
                return [jax.ShapeDtypeStruct(
                    (n,) + tuple(s.shape[1:]), np.dtype(s.dtype))
                    if i in batched_in else jax.ShapeDtypeStruct(
                        tuple(s.shape), np.dtype(s.dtype))
                    for i, s in enumerate(input_spec)]

            meta["batched_inputs"] = batched_in
            if base_b is not None:
                try:
                    o1 = jax.tree_util.tree_leaves(jax.eval_shape(
                        pure, p_specs, b_specs, *specs_at(base_b)))
                    o2 = jax.tree_util.tree_leaves(jax.eval_shape(
                        pure, p_specs, b_specs, *specs_at(base_b + 1)))
                    meta["batched_outputs"] = [
                        len(a.shape) > 0 and a.shape != b.shape
                        for a, b in zip(o1, o2)]
                except Exception:
                    pass             # serving falls back to heuristic
            if batch_buckets:
                # one artifact per batch bucket: the serving Predictor
                # pads a request up to the nearest bucket (reference
                # predictors re-run shape inference per batch; XLA
                # compiles per shape, so buckets bound the compile set).
                # meta records only buckets whose file was WRITTEN — a
                # mid-loop failure must not advertise missing artifacts.
                done = []
                for n in sorted(int(b) for b in batch_buckets):
                    bspecs = specs_at(n)
                    ex_n = jax_export.export(jax.jit(pure))(
                        p_specs, b_specs, *bspecs)
                    with open(f"{path}.pdmodel.b{n}.bin", "wb") as f:
                        f.write(ex_n.serialize())
                    done.append(n)
                meta["batch_buckets"] = done
        except Exception as e:  # pragma: no cover
            meta["export_error"] = str(e)
        try:
            lowered = jax.jit(pure).lower(p_specs, b_specs, *specs)
            with open(path + ".pdmodel", "w") as f:
                f.write(lowered.as_text())
            meta["stablehlo"] = True
        except Exception as e:  # pragma: no cover
            meta["stablehlo_error"] = str(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class LoadedLayer:
    """A model loaded from ``jit.save`` artifacts — runs the serialized
    program, no Python class needed (reference: TranslatedLayer,
    fluid/dygraph/io.py). Inference-only (the artifact is the traced
    forward)."""

    def __init__(self, path: str):
        from ..inference import Predictor

        self._predictor = Predictor(path)
        self.training = False

    def __call__(self, *args):
        outs = self._predictor.run(
            [a._value if isinstance(a, Tensor) else np.asarray(a)
             for a in args])
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        with open(self._predictor.path + ".pdparams", "rb") as f:
            return pickle.load(f)


def load(path, **config):
    """paddle.jit.load equivalent: returns a runnable LoadedLayer when the
    serialized program exists, else the raw state_dict (legacy saves)."""
    if os.path.exists(path + ".pdmodel.bin"):
        return LoadedLayer(path)
    with open(path + ".pdparams", "rb") as f:
        return pickle.load(f)


class TracedLayer:
    """reference: fluid/dygraph/jit.py TracedLayer(:1047)."""

    def __init__(self, layer):
        self._static = StaticLayer(layer)

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer)
        out = tl._static(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._static, path)
