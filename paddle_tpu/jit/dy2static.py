"""dy2static: AST conversion of data-dependent Python control flow.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — 25 files of
AST transformers (ifelse_transformer.py, loop_transformer.py,
program_translator.py:756 convert_to_static). The reference rewrites
`if`/`while` statements into convert_ifelse/convert_while_loop calls
that dispatch AT RUNTIME: a Variable condition builds cond/While ops, a
plain bool stays ordinary Python.

TPU-native translation of the same design: the transformer rewrites

    if COND: BODY else: ORELSE      ->  branch closures + _jst_ifelse
    while COND: BODY                ->  cond/body closures + _jst_while

and the _jst_* helpers dispatch on the condition's runtime type —
``Tensor`` (a jax tracer under to_static) routes to ``static.nn.cond``
/ ``static.nn.while_loop`` (lax.cond / lax.while_loop under jit), plain
Python values keep exact eager semantics. This closes the gap VERDICT
r4 missing #3 named: ``if tensor > 0:`` in user forward code now works
under tracing without a manual rewrite.

Scope (documented, with crisp errors for the rest): branches/loop
bodies that assign plain local names. `break`/`continue`/`return`
inside a transformed branch, tuple/attribute/subscript assignment
targets, and `global`/`nonlocal` leave that statement UNTRANSFORMED —
fine for bool conditions, and a tensor condition then raises an
actionable TracerBoolConversionError explanation instead of jax's raw
one.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Callable, Optional

__all__ = ["convert_to_static", "swapped_forward", "_jst_ifelse",
           "_jst_while", "control_flow_error_hint"]


def swapped_forward(target, converted_bound):
    """Context manager: temporarily install a converted bound forward on
    ``target`` (instance __dict__ only; the user's layer is untouched
    outside the scope). Shared by StaticLayer.__call__ tracing and
    jit.save's export trace."""
    from contextlib import contextmanager

    @contextmanager
    def cm():
        if converted_bound is None:
            yield
            return
        had = "forward" in target.__dict__
        prev = target.__dict__.get("forward")
        target.__dict__["forward"] = converted_bound
        try:
            yield
        finally:
            if had:
                target.__dict__["forward"] = prev
            else:
                target.__dict__.pop("forward", None)

    return cm()

_HELPERS = "__pt_jst_ifelse", "__pt_jst_while"


def _is_traced(x):
    """Tensor-valued (framework Tensor OR raw jax tracer): must route to
    cond/while ops. A concrete eager bool/ndarray keeps plain Python
    semantics. Layers invoked through functional_call receive raw jax
    values, so conditions can legitimately be bare tracers."""
    import jax

    from ..framework.tensor import Tensor

    if isinstance(x, Tensor):
        return isinstance(x._value, jax.core.Tracer)
    return isinstance(x, jax.core.Tracer)


def _wrap(v):
    from ..framework.tensor import Tensor

    return v if isinstance(v, Tensor) else Tensor(v)


class _Undef:
    """Placeholder for a carried local not yet bound before the
    statement (legal when both branches assign it). Any USE fails
    loudly with the original unbound-local semantics instead of letting
    the sentinel propagate."""

    def __repr__(self):
        return "<dy2static undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: a local variable carried through converted "
            "control flow was used before assignment (the taken branch "
            "never assigned it)")

    __getattr__ = _raise
    __call__ = _raise
    __bool__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = _raise
    __iter__ = __len__ = __getitem__ = _raise
    __eq__ = __ne__ = __hash__ = _raise
    __str__ = __format__ = _raise


_UNDEF = _Undef()


def _eval_thunks(thunks):
    out = []
    for t in thunks:
        try:
            out.append(t())
        except NameError:
            out.append(_UNDEF)
    return tuple(out)


def _jst_ifelse(cond, true_fn, false_fn, thunks, names):
    """Runtime dispatch for a transformed `if` (reference:
    dygraph_to_static/convert_operators.py convert_ifelse). Branch fns
    take the carried locals as PARAMETERS (a branch reassigning a name
    it also reads would otherwise hit UnboundLocalError in a no-arg
    closure); names unbound before the `if` enter as an undef sentinel
    and must be assigned by both branches under a tensor condition."""
    init = _eval_thunks(thunks)
    if not _is_traced(cond):
        import numpy as _np

        from ..framework.tensor import Tensor

        c = cond if isinstance(cond, bool) else bool(
            _np.asarray(cond._value if isinstance(cond, Tensor)
                        else cond))
        return _as_tuple(true_fn(*init) if c else false_fn(*init), names)
    from ..static.nn import cond as cond_op

    tv = true_fn(*init)
    fv = false_fn(*init)
    tv = tv if isinstance(tv, tuple) else (tv,)
    fv = fv if isinstance(fv, tuple) else (fv,)
    for branch, vals in (("true", tv), ("false", fv)):
        for n, v in zip(names, vals):
            if v is _UNDEF:
                raise NameError(
                    f"dy2static: `{n}` is not defined on the {branch} "
                    f"path of a tensor-condition `if`. Both branches "
                    f"trace, so every carried name "
                    f"({list(names)}) must be assigned on both paths "
                    f"or before the `if`.")
    out = cond_op(cond, lambda: tv, lambda: fv)
    return _as_tuple(out, names)


def _jst_while(cond_fn, body_fn, init, names):
    """Runtime dispatch for a transformed `while` (reference:
    convert_operators.py convert_while_loop)."""
    init = _eval_thunks(init)
    if any(v is _UNDEF for v in init):
        missing = [n for n, v in zip(names, init) if v is _UNDEF]
        raise NameError(
            f"dy2static: `while` loop variable(s) {missing} are not "
            f"initialized before the loop. Loops carry {list(names)} "
            f"through lax.while_loop, so each must be assigned before "
            f"the loop.")
    try:
        first = cond_fn(*init)
    except NameError as e:
        raise NameError(
            f"dy2static: a name read in the `while` condition is not "
            f"defined before the loop ({e}).") from e
    if not _is_traced(first):
        import numpy as _np

        from ..framework.tensor import Tensor

        def concrete(c):
            return bool(_np.asarray(c._value if isinstance(c, Tensor)
                                    else c))

        vals = init
        while concrete(cond_fn(*vals)):
            out = body_fn(*vals)
            vals = out if isinstance(out, tuple) else (out,)
        return vals
    from ..static.nn import while_loop as while_op

    out = while_op(cond_fn, body_fn, [_wrap(v) for v in init])
    return _as_tuple(out, names)


def _as_tuple(out, names):
    if isinstance(out, list):
        out = tuple(out)
    if len(names) == 1:
        if isinstance(out, tuple) and len(out) == 1:
            return out
        return (out,)
    return tuple(out)


def _assigned_names(stmts):
    """Plain local names assigned in a statement list; None when an
    unsupported construct appears (the caller then skips the node)."""
    names = set()

    class Scan(ast.NodeVisitor):
        ok = True

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.target is not None:
                self._target(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):   # walrus binds a local too
            self._target(node.target)
            self.generic_visit(node)

        def visit_With(self, node):
            for item in node.items:
                if item.optional_vars is not None:
                    self._target(item.optional_vars)
            self.generic_visit(node)

        def visit_Import(self, node):      # noqa: N802
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])

        def visit_ImportFrom(self, node):  # noqa: N802
            for alias in node.names:
                names.add(alias.asname or alias.name)

        def _target(self, t):
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)
            elif isinstance(t, (ast.Attribute, ast.Subscript)):
                pass                       # side effect, not a local bind
            else:
                self.ok = False

        def visit_Return(self, node):      # noqa: N802
            self.ok = False

        def visit_Break(self, node):       # noqa: N802
            self.ok = False

        def visit_Continue(self, node):    # noqa: N802
            self.ok = False

        def visit_Global(self, node):      # noqa: N802
            self.ok = False

        def visit_Nonlocal(self, node):    # noqa: N802
            self.ok = False

        def visit_FunctionDef(self, node):  # don't descend into defs
            pass

        def visit_Lambda(self, node):
            pass

    s = Scan()
    for st in stmts:
        s.visit(st)
    return sorted(names) if s.ok else None


class _ControlFlowTransformer(ast.NodeTransformer):
    """ifelse_transformer + loop_transformer in one pass."""

    def __init__(self):
        self.counter = 0
        self.skipped = []                   # (lineno, reason)

    def _fresh(self):
        self.counter += 1
        return self.counter

    def visit_If(self, node):
        self.generic_visit(node)            # post-order: inner first
        names = _assigned_names(node.body)
        names_else = _assigned_names(node.orelse)
        if names is None or names_else is None:
            self.skipped.append(
                (node.lineno, "if-branch uses return/break/continue or "
                              "non-name assignment"))
            return node
        out = sorted(set(names) | set(names_else))
        if not out:
            # branches only produce side effects; leave untouched
            self.skipped.append((node.lineno, "if-branch assigns no "
                                              "local names"))
            return node
        k = self._fresh()
        tname, fname = f"__pt_true_{k}", f"__pt_false_{k}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in out],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in out],
            ctx=ast.Load()))
        tdef = ast.FunctionDef(
            name=tname, args=args, body=list(node.body) + [ret],
            decorator_list=[])
        fdef = ast.FunctionDef(
            name=fname, args=args,
            body=(list(node.orelse) or [ast.Pass()]) + [ret],
            decorator_list=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in out],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id=_HELPERS[0], ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      _thunk_tuple(out),
                      _name_tuple(out)],
                keywords=[]))
        return [tdef, fdef, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            self.skipped.append((node.lineno, "while-else not supported"))
            return node
        names = _assigned_names(node.body)
        if names is None:
            self.skipped.append(
                (node.lineno, "while-body uses return/break/continue or "
                              "non-name assignment"))
            return node
        if not names:
            self.skipped.append((node.lineno, "while-body assigns no "
                                              "local names"))
            return node
        k = self._fresh()
        cname, bname = f"__pt_wcond_{k}", f"__pt_wbody_{k}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cdef = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
            ctx=ast.Load()))
        bdef = ast.FunctionDef(
            name=bname, args=args, body=list(node.body) + [ret],
            decorator_list=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id=_HELPERS[1], ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      _thunk_tuple(names),
                      _name_tuple(names)],
                keywords=[]))
        return [cdef, bdef, assign]


def _noargs():
    return ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                         kw_defaults=[], defaults=[])


def _name_tuple(names):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load())


def _thunk_tuple(names):
    """(lambda: a, lambda: b) — deferring each name's read so an unbound
    local surfaces as a helper-level sentinel, not a call-site
    NameError."""
    return ast.Tuple(
        elts=[ast.Lambda(args=_noargs(),
                         body=ast.Name(id=n, ctx=ast.Load()))
              for n in names],
        ctx=ast.Load())


def control_flow_error_hint(skipped=None):
    lines = ["dy2static could not stage this Python control flow for "
             "jit: the condition is a traced Tensor but the statement "
             "was not convertible."]
    for ln, why in (skipped or []):
        lines.append(f"  - line {ln}: {why}")
    lines.append(
        "Rewrite the statement with static.nn.cond / "
        "static.nn.while_loop (or masked tensor ops), or restructure "
        "the branch to assign plain local names without "
        "return/break/continue.")
    return "\n".join(lines)


def convert_to_static(fn: Callable) -> Optional[Callable]:
    """AST-convert ``fn``'s tensor-dependent if/while. Returns the
    converted function, or None when nothing needed conversion or the
    source is unavailable (caller keeps the original).

    Closure cells are preserved by recompiling inside a factory whose
    parameters mirror co_freevars (the reference's program_translator
    re-executes the transformed source the same way)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    has_cf = any(isinstance(n, (ast.If, ast.While))
                 for n in ast.walk(tree))
    if not has_cf:
        return None
    # zero-arg super() relies on the class-body-compiled __class__ cell;
    # a factory recompile cannot reproduce that linkage faithfully —
    # leave such forwards unconverted (bool conditions keep working;
    # tensor conditions get jax's tracer error)
    if any(isinstance(n, ast.Name) and n.id == "super"
           for n in ast.walk(tree)):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    tr = _ControlFlowTransformer()
    tr.visit(fdef)
    if tr.counter == 0:
        return None
    ast.fix_missing_locations(tree)

    freevars = fn.__code__.co_freevars
    cells = fn.__closure__ or ()
    if freevars:
        factory = ast.FunctionDef(
            name="__pt_factory__",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fdef, ast.Return(value=ast.Name(id=fdef.name,
                                                  ctx=ast.Load()))],
            decorator_list=[])
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(mod)
    code = compile(mod, f"<dy2static {getattr(fn, '__qualname__', fn)}>",
                   "exec")
    glb = dict(fn.__globals__)
    glb[_HELPERS[0]] = _jst_ifelse
    glb[_HELPERS[1]] = _jst_while
    ns = {}
    exec(code, glb, ns)
    if freevars:
        new_fn = ns["__pt_factory__"](*[c.cell_contents for c in cells])
    else:
        new_fn = ns[fdef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__dy2static_skipped__ = tr.skipped
    new_fn.__wrapped__ = fn
    return new_fn
