"""Custom-op SDK: out-of-tree ops without touching the framework.

Reference analogue (SURVEY §2 N40): the C++ extension SDK —
``PD_BUILD_OP`` macros (reference: extension/include/op_meta_info.h),
runtime dylib loading (framework/custom_operator.cc LoadOpMetaInfoAndRegisterOp)
and the minimal C ABI (framework/c/c_api.h, N48).

TPU-native translation, two tiers:

  1. ``register_op(name, forward, backward=...)`` — the op is a JAX/Pallas
     function (this is where TPU "kernels" live; a Pallas kernel IS the
     CUDA-kernel analogue). Registered ops get a tape-level Tensor entry
     under ``paddle_tpu.ops.custom.<name>`` with a custom VJP, exactly
     like in-tree ops (ops/flash_attention.py).

  2. ``load_op_library(path)`` — dlopen a native shared library of
     HOST-side ops using a small C ABI (see below) and register each as a
     jax.pure_callback op: runs on the host inside jitted programs — the
     role the reference's custom C++ CPU kernels played.

Native C ABI (mirrors the spirit of framework/c/c_api.h):

    int32_t     ptl_num_ops(void);
    const char* ptl_op_name(int32_t i);
    // elementwise double op applied to n values: out may alias in
    void        ptl_op_apply(int32_t i, const double* in, int64_t n,
                             double* out);
"""
from __future__ import annotations

import ctypes
from typing import Callable, Dict, Optional

import jax
import numpy as np

from ..framework.tensor import Tensor
from ..tensor._helper import apply

_REGISTRY: Dict[str, Callable] = {}


class _CustomNamespace:
    """Attribute access to registered ops: paddle_tpu.ops.custom.<name>."""

    def __getattr__(self, name):
        try:
            return _REGISTRY[name]
        except KeyError:
            raise AttributeError(
                f"no custom op {name!r}; registered: "
                f"{sorted(_REGISTRY)}") from None


def get_op(name: str) -> Callable:
    return _REGISTRY[name]


def register_op(name: str, forward: Callable,
                backward: Optional[Callable] = None,
                num_inputs: Optional[int] = None) -> Callable:
    """Register a jax-level function as a framework op.

    forward(*jnp_arrays) -> jnp array (or tuple). backward(res, grad) ->
    tuple of input grads, where res = (inputs, output). When backward is
    omitted, jax's autodiff of `forward` applies (forward must then be
    differentiable jax code).
    """
    if backward is not None:
        import functools

        @functools.partial(jax.custom_vjp)
        def core(*args):
            return forward(*args)

        def fwd(*args):
            out = forward(*args)
            return out, (args, out)

        def bwd(res, g):
            grads = backward(res, g)
            return tuple(grads)

        core.defvjp(fwd, bwd)
    else:
        core = forward

    def tape_entry(*tensors, **kw):
        ins = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
               for t in tensors]
        return apply(lambda *vals: core(*vals, **kw), *ins,
                     name=f"custom.{name}")

    tape_entry.__name__ = name
    _REGISTRY[name] = tape_entry

    # surface it at paddle_tpu.ops.custom.<name>
    from .. import ops as _ops

    if not hasattr(_ops, "custom"):
        _ops.custom = _CustomNamespace()
    return tape_entry


def load_op_library(path: str):
    """Load a native shared library of host ops (C ABI in the module
    docstring) and register each op. Returns the list of op names.

    reference: paddle.utils.cpp_extension.load / custom_operator.cc —
    the dylib route for out-of-tree native kernels."""
    lib = ctypes.CDLL(path)
    lib.ptl_num_ops.restype = ctypes.c_int32
    lib.ptl_op_name.restype = ctypes.c_char_p
    lib.ptl_op_name.argtypes = [ctypes.c_int32]
    lib.ptl_op_apply.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double)]

    names = []
    for i in range(lib.ptl_num_ops()):
        op_name = lib.ptl_op_name(i).decode()

        def host_call(x, _i=i):
            x64 = np.ascontiguousarray(np.asarray(x, np.float64))
            out = np.empty_like(x64)
            lib.ptl_op_apply(
                _i, x64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                x64.size, out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_double)))
            return out.astype(np.asarray(x).dtype)

        def fwd(x, _hc=host_call):
            # host round-trip op: runs the native kernel inside jit
            return jax.pure_callback(
                lambda v: _hc(v), jax.ShapeDtypeStruct(x.shape, x.dtype),
                x, vmap_method="sequential")

        register_op(op_name, fwd)
        names.append(op_name)
    return names
