"""paddle.utils.run_check (reference: utils/install_check.py:134).

The reference trains a 2-layer FC single- and multi-GPU to prove the
install works; here the check runs a matmul+grad on the default device
and an 8-device SPMD matmul on the virtual CPU mesh (the multi-chip
path's compile check).
"""
from __future__ import annotations

__all__ = ["run_check"]


def run_check():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle

    dev = jax.devices()[0]
    print(f"Running verify PaddlePaddle(TPU) program ... "
          f"[device: {dev.platform}:{dev.id}]")

    # 1) eager forward + backward on the default device
    paddle.seed(0)
    net = paddle.nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = net(x).sum()
    loss.backward()
    assert net.weight.grad is not None
    float(np.asarray(loss._value))

    # 2) compiled SPMD matmul over every local device
    n = len(jax.devices())
    if n > 1:
        from jax.sharding import (Mesh, NamedSharding,
                                  PartitionSpec as P)

        mesh = Mesh(np.array(jax.devices()).reshape(n), ("dp",))
        a = jax.device_put(jnp.ones((n * 2, 8), jnp.float32),
                           NamedSharding(mesh, P("dp")))
        out = jax.jit(lambda v: (v @ v.T).sum())(a)
        assert float(out) > 0
        print(f"PaddlePaddle(TPU) works well on {n} devices.")
    print("PaddlePaddle(TPU) is installed successfully!")
