"""Small bounded LRU map for per-shape executable/jit caches.

A serving loop feeds the per-shape caches an unbounded key stream
(every distinct batch/length combination mints a compiled program), so
the dicts that were "cache forever" under training workloads become a
slow leak under serving. This LRU keeps the hot shapes and counts what
it drops: every eviction increments the ``cache_evict/<name>`` counter
in the profiler registry, so a serving deployment whose shape traffic
exceeds the cap is visible in ``profiler.summary()`` instead of showing
up only as mysterious recompiles.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Optional


class LRUCache:
    """dict-ish bounded mapping with least-recently-used eviction.

    ``on_evict(key, value)`` runs for every evicted entry (executable
    caches use it to drop companion state keyed by the same object).
    """

    def __init__(self, capacity: int, name: str = "lru",
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        if capacity < 1:
            raise ValueError(f"LRU capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.name = name
        self.on_evict = on_evict
        self._d: OrderedDict = OrderedDict()
        self.evictions = 0

    def get(self, key, default=None):
        try:
            self._d.move_to_end(key)
        except KeyError:
            return default
        return self._d[key]

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            k, v = self._d.popitem(last=False)
            self.evictions += 1
            self._count_eviction()
            if self.on_evict is not None:
                self.on_evict(k, v)

    def _count_eviction(self) -> None:
        from ..profiler import registry

        registry().counter(f"cache_evict/{self.name}").add(1)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value) -> None:
        self.put(key, value)

    def keys(self):
        return self._d.keys()

    def values(self):
        return self._d.values()

    def clear(self) -> None:
        self._d.clear()


_MISSING = object()
