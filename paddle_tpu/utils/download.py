"""Weight/dataset path resolution (reference: utils/download.py).

This environment has no network egress, so URL fetches resolve strictly
from the local cache (~/.cache/paddle/...). A cache hit returns the
path; a miss raises with the exact path to place the file at — the
download machinery's contract without the network dependency.
"""
from __future__ import annotations

import os

from .retry import RetryError, retry

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle/hapi/weights")


def _read_bytes(path: str) -> bytes:
    """Cached-file read behind retry: network filesystems (the cache dir
    may be NFS/FUSE on a fleet host) throw transient OSErrors that a
    couple of backoff attempts absorb (shared resilience retry())."""
    def _once():
        with open(path, "rb") as f:
            return f.read()

    try:
        return retry(_once, attempts=3, base_delay=0.05,
                     exceptions=(OSError,))
    except RetryError as e:
        raise e.last    # callers catch OSError/FileNotFoundError


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir, fname)
    if os.path.exists(path):
        if md5sum:
            import hashlib

            got = hashlib.md5(_read_bytes(path)).hexdigest()
            if got != md5sum:
                raise RuntimeError(
                    f"cached file {path} is corrupt: md5 {got} != "
                    f"expected {md5sum}. Delete it and re-place the "
                    "correct file (no network egress here).")
        return path
    raise RuntimeError(
        f"cannot download {url}: this environment has no network "
        f"egress. Place the file at {path} and retry.")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
