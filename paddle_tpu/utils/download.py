"""Weight/dataset path resolution (reference: utils/download.py).

This environment has no network egress, so URL fetches resolve strictly
from the local cache (~/.cache/paddle/...). A cache hit returns the
path; a miss raises with the exact path to place the file at — the
download machinery's contract without the network dependency.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle/hapi/weights")


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir, fname)
    if os.path.exists(path):
        if md5sum:
            import hashlib

            with open(path, "rb") as f:
                got = hashlib.md5(f.read()).hexdigest()
            if got != md5sum:
                raise RuntimeError(
                    f"cached file {path} is corrupt: md5 {got} != "
                    f"expected {md5sum}. Delete it and re-place the "
                    "correct file (no network egress here).")
        return path
    raise RuntimeError(
        f"cannot download {url}: this environment has no network "
        f"egress. Place the file at {path} and retry.")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
