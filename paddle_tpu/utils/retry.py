"""retry(): bounded retry with exponential backoff.

One small utility shared by every host-side I/O path that may see
transient failures — data-loader calls (resilience/runner.py), cached
weight reads (utils/download.py), checkpoint directory listings. Kept
deliberately tiny and deterministic: with ``jitter=0`` the sleep
sequence is ``base_delay * factor**k`` capped at ``max_delay``, so tests
can assert the exact schedule.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["retry", "RetryError"]


class RetryError(RuntimeError):
    """All attempts exhausted; ``last`` carries the final exception."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"retry: {attempts} attempt(s) failed; last error: {last!r}")
        self.attempts = attempts
        self.last = last


def backoff_delays(attempts: int, base_delay: float, factor: float,
                   max_delay: float, jitter: float = 0.0,
                   seed: Optional[int] = None):
    """The sleep schedule between attempts (attempts-1 entries).
    ``jitter`` adds a uniform [0, jitter*delay) term; deterministic when
    a seed is given (fleet-wide thundering-herd avoidance without
    nondeterministic tests)."""
    rng = random.Random(seed) if jitter else None
    out = []
    for k in range(max(0, attempts - 1)):
        d = min(base_delay * (factor ** k), max_delay)
        if rng is not None:
            d += rng.uniform(0.0, jitter * d)
        out.append(d)
    return out


def retry(fn: Optional[Callable] = None, *,
          attempts: int = 4,
          base_delay: float = 0.05,
          factor: float = 2.0,
          max_delay: float = 5.0,
          jitter: float = 0.0,
          seed: Optional[int] = None,
          exceptions: Tuple[Type[BaseException], ...] = (Exception,),
          on_retry: Optional[Callable] = None,
          sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` up to ``attempts`` times with exponential backoff.

    Usable three ways::

        retry(lambda: flaky())                 # immediate call
        @retry(attempts=6, exceptions=(OSError,))
        def load(): ...                        # decorator with options
        wrapped = retry(load, attempts=6)      # wrap, call later? no —
                                               # positional fn is CALLED

    A positional ``fn`` is invoked immediately and its result returned
    (the common inline case); with no positional argument a decorator is
    returned. ``on_retry(attempt_index, exception, delay)`` observes
    every failed attempt that will be retried (the resilience runner
    counts these into ``resilience/data_retries``).
    """
    delays = backoff_delays(attempts, base_delay, factor, max_delay,
                            jitter=jitter, seed=seed)

    def _run(f, *args, **kwargs):
        last: Optional[BaseException] = None
        for i in range(attempts):
            try:
                return f(*args, **kwargs)
            except exceptions as e:   # noqa: PERF203 - retry loop
                last = e
                if i >= attempts - 1:
                    break
                d = delays[i]
                if on_retry is not None:
                    on_retry(i, e, d)
                if d > 0:
                    sleep(d)
        raise RetryError(attempts, last)

    if fn is not None:
        return _run(fn)

    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return _run(f, *args, **kwargs)

        return wrapper

    return deco
