"""Utility surface (reference: python/paddle/utils/)."""
from . import custom_op  # noqa: F401
from .custom_op import get_op, load_op_library, register_op  # noqa: F401
