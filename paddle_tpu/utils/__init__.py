"""Utility surface (reference: python/paddle/utils/)."""
from . import custom_op, download, retry  # noqa: F401
from .custom_op import get_op, load_op_library, register_op  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
# NOTE: the retry FUNCTION is `paddle_tpu.utils.retry.retry` — rebinding
# it here would shadow the submodule attribute and break
# `import paddle_tpu.utils.retry`
from .retry import RetryError  # noqa: F401
from .install_check import run_check  # noqa: F401
from .lazy_import import try_import  # noqa: F401
