"""@deprecated decorator (reference: python/paddle/utils/deprecated.py)."""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(update_to="", since="", reason=""):
    """Mark an API deprecated: warns once per call site with the
    suggested replacement, same contract as the reference decorator."""
    def decorator(func):
        msg = f"API \"{func.__module__}.{func.__name__}\" is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", and will be removed in future versions. Please "\
                   f"use \"{update_to}\" instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator
