"""try_import (reference: python/paddle/utils/lazy_import.py)."""
from __future__ import annotations

import importlib

__all__ = ["try_import"]


def try_import(module_name, err_msg=None):
    """Import a soft dependency, raising a helpful ImportError when it
    is absent (the reference suggests the pip package name)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        if err_msg is None:
            err_msg = (
                f"Failed importing {module_name}. This likely means "
                f"that some modules require additional dependencies "
                f"that have to be manually installed (usually with "
                f"`pip install {module_name}`).")
        raise ImportError(err_msg) from e
