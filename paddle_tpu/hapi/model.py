"""High-level Model API (reference: python/paddle/hapi/model.py — Model:810,
fit:1299, DynamicGraphAdapter:609).

The adapter split of the reference (static vs dygraph) collapses here: one
adapter that runs the network through the jit'd functional path for speed
while exposing the eager state (state_dict etc.) unchanged.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..autograd import no_grad
from ..framework.tensor import Tensor
from ..metric import Metric
from ..profiler import instrument as _pinstr
from ..profiler import trace as _ptrace
from ..profiler.metrics import registry as _preg
from .callbacks import config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    # -- single-batch ops --------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if callable(self._loss):
            return self._loss(*(list(outs) + list(lbls)))
        raise ValueError("loss is not set; call prepare(loss=...)")

    def train_batch(self, inputs, labels=None, update=True):
        # profiler hook: one bool read when disabled; enabled, the batch
        # is a host span and the train counters move (ProfilerCallback
        # or a manual profiler.enable() both land here)
        if _ptrace.is_enabled():
            with _ptrace.scope("hapi/train_batch"):
                res = self._train_batch_impl(inputs, labels, update)
            ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
            # shape-only accounting: never np.asarray a device array here
            # (a d2h copy of the batch would perturb the step timings)
            vals = [x._value if isinstance(x, Tensor) else x for x in ins]
            vals = [v if hasattr(v, "shape") else np.asarray(v)
                    for v in vals]
            reg = _preg()
            reg.counter("train/steps").add(1)
            reg.counter("train/tokens").add(_pinstr.tokens_in_batch(vals))
            _pinstr.record_memory_high_water()
            return res
        return self._train_batch_impl(inputs, labels, update)

    def _train_batch_impl(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            lbls = labels if isinstance(labels, (list, tuple)) else [labels]
            m_in = m.compute(outputs, *lbls)
            metrics.append(m.update(m_in.numpy()
                                    if isinstance(m_in, Tensor) else m_in))
        return ([loss.numpy()] + metrics) if metrics else [loss.numpy()]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        if _ptrace.is_enabled():
            with _ptrace.scope("hapi/eval_batch"):
                res = self._eval_batch_impl(inputs, labels)
            _preg().counter("eval/steps").add(1)
            return res
        return self._eval_batch_impl(inputs, labels)

    def _eval_batch_impl(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        outputs = self.network(*ins)
        losses = []
        if self._loss is not None and labels is not None:
            losses = [self._compute_loss(outputs, labels).numpy()]
        metrics = []
        for m in self._metrics:
            lbls = labels if isinstance(labels, (list, tuple)) else [labels]
            m_in = m.compute(outputs, *lbls)
            metrics.append(m.update(m_in.numpy()
                                    if isinstance(m_in, Tensor) else m_in))
        return losses + metrics if metrics else losses

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        out = self.network(*ins)
        if isinstance(out, (list, tuple)):
            return [o.numpy() for o in out]
        return [out.numpy()]

    # -- loops -------------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle):
        from ..io import DataLoader, Dataset

        if data is None or hasattr(data, "__iter__") and not isinstance(
                data, Dataset):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._to_loader(train_data, batch_size, shuffle)
        eval_loader = self._to_loader(eval_data, batch_size, False)
        try:
            steps = len(train_loader)
        except Exception:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir,
                                metrics=["loss"] + [m.name()
                                                    for m in self._metrics])
        cbks.on_train_begin()
        self.stop_training = False
        try:
            self._fit_epochs(cbks, train_loader, eval_loader, epochs,
                             eval_freq, accumulate_grad_batches,
                             num_iters)
        finally:
            # ALWAYS runs, also when a batch raises: callbacks with
            # global side effects (PreemptionSave's signal handlers,
            # ProfilerCallback's enabled profiler/device trace) must
            # tear them down or they outlive the failed fit
            cbks.on_train_end(self._last_fit_logs)

    def _fit_epochs(self, cbks, train_loader, eval_loader, epochs,
                    eval_freq, accumulate_grad_batches, num_iters):
        self._last_fit_logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, lbls = self._split_batch(batch)
                res = self.train_batch(ins, lbls,
                                       update=(step + 1) %
                                       accumulate_grad_batches == 0)
                logs = self._make_logs(res)
                self._last_fit_logs = logs
                cbks.on_train_batch_end(step, logs)
                # honored PER BATCH: TerminateOnNaN must stop before
                # more poisoned updates land, and PreemptionSave must
                # exit inside the preemption grace window — an
                # epoch-boundary-only check defeats both
                if self.stop_training:
                    break
                if num_iters is not None and step + 1 >= num_iters:
                    break
            cbks.on_epoch_end(epoch, logs)
            # stop BEFORE the epoch-tail eval: a preemption (or NaN
            # stop) at batch k must not pay a full eval pass — on a
            # fleet SIGTERM that pushes the exit past the grace window
            # and the promised prompt resumable exit is SIGKILLed
            # mid-eval instead
            if self.stop_training:
                break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, verbose=0, callbacks=None)
                eval_logs = {m.name()[0] if isinstance(m.name(), list)
                             else m.name(): m.accumulate()
                             for m in self._metrics}
                cbks.on_eval_end(eval_logs)

    @no_grad()
    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._to_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        last = []
        for step, batch in enumerate(loader):
            ins, lbls = self._split_batch(batch)
            last = self.eval_batch(ins, lbls)
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = self._make_logs(last)
        for m in self._metrics:
            name = m.name()
            logs[name[0] if isinstance(name, list) else name] = m.accumulate()
        return logs

    @no_grad()
    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            ins = batch[0] if isinstance(batch, (list, tuple)) else batch
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[0], batch[1]
        return batch, None

    def _make_logs(self, res):
        logs = {}
        if res:
            logs["loss"] = float(np.asarray(res[0]).reshape(-1)[0])
        for m, v in zip(self._metrics, res[1:]):
            name = m.name()
            logs[name[0] if isinstance(name, list) else name] = \
                float(np.asarray(v).reshape(-1)[0]) \
                if not isinstance(v, list) else v
        return logs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save

        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        from ..framework.io import load as _load

        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size)
