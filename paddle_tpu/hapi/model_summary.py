"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    total_params = 0
    trainable_params = 0
    lines = [f"{'Layer (type)':<40}{'Param #':>12}"]
    lines.append("-" * 52)
    for name, layer in net.named_sublayers(include_self=True):
        n = 0
        for _, p in layer.named_parameters(include_sublayers=False):
            n += int(np.prod(p.shape))
        if name == "":
            continue
        lines.append(f"{name + ' (' + type(layer).__name__ + ')':<40}"
                     f"{n:>12,}")
    for _, p in net.named_parameters():
        c = int(np.prod(p.shape))
        total_params += c
        if p.trainable:
            trainable_params += c
    lines.append("-" * 52)
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    lines.append(
        f"Non-trainable params: {total_params - trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params,
            "trainable_params": trainable_params}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs via jax cost analysis on the jitted forward."""
    import jax

    from ..static.functional import functional_call, state_tensors

    pn, pt, bn, bt = state_tensors(net)
    x = jax.ShapeDtypeStruct(tuple(input_size), np.float32)

    def pure(p_vals, b_vals, xv):
        out, _ = functional_call(net, p_vals, b_vals, (xv,), training=False)
        return out

    try:
        lowered = jax.jit(pure).lower(
            [jax.ShapeDtypeStruct(p._value.shape, p._value.dtype)
             for p in pt],
            [jax.ShapeDtypeStruct(b._value.shape, b._value.dtype)
             for b in bt], x)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return int(cost.get("flops", 0))
    except Exception:
        return 0
