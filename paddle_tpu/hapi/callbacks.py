"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """reference: hapi/callbacks.py ProgBarLogger."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            metrics = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else
                f"{k}: {v}" for k, v in (logs or {}).items())
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch + 1} step {step}{total} - {metrics}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            metrics = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else
                f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {metrics}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            metrics = " - ".join(
                f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Eval - {metrics}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.is_better = lambda cur, best: cur > best + self.min_delta
            self.best = float("-inf")
        else:
            self.is_better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.is_better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._lr_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ProfilerCallback(Callback):
    """Profile a ``Model.fit`` run with paddle_tpu.profiler.

    Enables the profiler once ``skip_steps`` train batches have run (the
    default 1 keeps the first batch's compile out of the statistics),
    lets Model.train_batch's own instrumentation record per-batch spans
    and train/steps + train/tokens counters, and on train end writes
    ``summary.json`` (profiler.summary(): scopes, metrics, rates,
    phases, retraces) plus ``trace.json`` (chrome://tracing) into
    ``log_dir``, then disables the profiler.

    ``trace_dir``: additionally start a jax/XLA device trace into that
    directory while profiling (TensorBoard-loadable; TPU timelines).
    """

    def __init__(self, log_dir="./profile", skip_steps=1,
                 export_chrome=True, trace_dir=None):
        super().__init__()
        self.log_dir = log_dir
        self.skip_steps = max(0, int(skip_steps))
        self.export_chrome = export_chrome
        self.trace_dir = trace_dir
        self._seen = 0

    def _profiler(self):
        from .. import profiler

        return profiler

    def on_train_begin(self, logs=None):
        self._seen = 0
        if self.skip_steps == 0:
            self._profiler().enable(trace_dir=self.trace_dir)

    def on_train_batch_end(self, step, logs=None):
        self._seen += 1
        p = self._profiler()
        if not p.is_enabled() and self._seen >= self.skip_steps:
            p.enable(trace_dir=self.trace_dir)

    def on_train_end(self, logs=None):
        import json

        p = self._profiler()
        if not p.is_enabled():
            return
        os.makedirs(self.log_dir, exist_ok=True)
        if self.export_chrome:
            p.export_chrome_trace(os.path.join(self.log_dir, "trace.json"))
        summary = p.disable()
        with open(os.path.join(self.log_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2, default=float)


class TerminateOnNaN(Callback):
    """Stop ``Model.fit`` when the batch loss goes non-finite — the
    hapi-level cousin of the trainer's compiled bad-step guard
    (distributed/hybrid.py guard_bad_steps). fit() loops have no
    update-skip hook, so the safe reaction is to stop before more
    poisoned updates land; the per-event counter rides the same
    ``resilience/*`` namespace the runner uses."""

    def __init__(self, monitor="loss"):
        super().__init__()
        self.monitor = monitor
        self.stopped_step = None

    def on_train_batch_end(self, step, logs=None):
        import math

        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0] if cur else None
        try:
            v = float(cur)
        except (TypeError, ValueError):
            return
        if math.isnan(v) or math.isinf(v):
            from ..profiler.metrics import registry

            registry().counter("resilience/nan_terminations").add(1)
            self.stopped_step = step
            print(f"TerminateOnNaN: {self.monitor}={v} at step {step}; "
                  f"stopping training")
            self.model.stop_training = True


class PreemptionSave(Callback):
    """Graceful-preemption for ``Model.fit``: installs the resilience
    SIGTERM/SIGINT handler for the duration of training; on a request
    it saves the model into ``save_dir`` after the in-flight batch and
    stops the fit loop, so a supervisor restart resumes from the saved
    weights instead of losing the epoch.

    ``manager``: optional object with a ``wait()`` method (a
    ``CheckpointManager`` / ``ElasticTrainer.manager``) joined BEFORE
    the preemption save — with the async step pipeline's streamed
    snapshots a prior periodic save may still be copying/writing in the
    background, and the preemption exit must not race it (the same
    flush the resilient runner's preemption path performs)."""

    def __init__(self, save_dir, name="preempted", manager=None):
        super().__init__()
        self.save_dir = save_dir
        self.name = name
        self.manager = manager
        self.preempted = False
        self._handler = None

    def on_train_begin(self, logs=None):
        from ..resilience.preemption import PreemptionHandler

        self.preempted = False
        self._handler = PreemptionHandler().install()

    def on_train_batch_end(self, step, logs=None):
        h = self._handler
        if h is None or not h.requested or self.preempted:
            return
        from ..profiler.metrics import registry

        self.preempted = True
        if self.manager is not None:       # join in-flight async saves
            self.manager.wait()
        os.makedirs(self.save_dir, exist_ok=True)
        self.model.save(os.path.join(self.save_dir, self.name))
        registry().counter("resilience/preemptions").add(1)
        self.model.stop_training = True

    def on_train_end(self, logs=None):
        if self._handler is not None:
            self._handler.uninstall()
            self._handler = None


class VisualDL(Callback):
    """Metrics writer (reference: hapi/callbacks.py VisualDL); writes a
    jsonl metrics log instead of the visualdl binary format."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None

    def on_train_begin(self, logs=None):
        self._f = open(os.path.join(self.log_dir, "metrics.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        import json

        if self._f:
            rec = {"step": step}
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    rec[k] = float(v)
            self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
