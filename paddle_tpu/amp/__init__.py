"""Automatic mixed precision (reference: python/paddle/amp/auto_cast.py:20,
grad_scaler.py:20; trace-time autocast tracer.cc:159-162, lists
contrib/mixed_precision/fp16_lists.py:34-38).

TPU-native: the compute dtype is bfloat16 — same exponent range as fp32 —
so dynamic loss scaling is unnecessary (SURVEY §7 translation table). The
autocast context casts inputs of matmul-class ops to bf16 at op-dispatch
time exactly like the reference's tracer autocast; GradScaler is kept
API-compatible and becomes a no-op scaler by default (enable fp16-style
scaling explicitly if requested).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..framework.tensor import Tensor

# reference fp16_lists.py white/black lists, adapted
WHITE_LIST = {"matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
              "conv2d_transpose", "einsum", "sdpa", "flash_attention"}
BLACK_LIST = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "log_softmax", "cross_entropy", "layer_norm", "batch_norm",
              "softmax_with_cross_entropy"}

_state = threading.local()


def _amp_state():
    if not hasattr(_state, "enabled"):
        _state.enabled = False
        _state.dtype = jnp.bfloat16
        _state.white = set(WHITE_LIST)
        _state.black = set(BLACK_LIST)
        _state.level = "O1"
    return _state


def amp_cast_inputs(op_name, vals):
    """Called from autograd.tape.apply on tensor input values."""
    s = _amp_state()
    if not s.enabled:
        return vals
    if s.level == "O2":
        # cast everything float except blacklist
        if op_name in s.black:
            tgt = jnp.float32
        else:
            tgt = s.dtype
    elif op_name in s.white:
        tgt = s.dtype
    elif op_name in s.black:
        tgt = jnp.float32
    else:
        return vals
    out = []
    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(jnp.asarray(v).dtype,
                                                  jnp.floating):
            out.append(jnp.asarray(v).astype(tgt))
        else:
            out.append(v)
    return out


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast equivalent."""
    s = _amp_state()
    prev = (s.enabled, s.white.copy(), s.black.copy(), s.level, s.dtype)
    s.enabled = enable
    s.level = level
    s.dtype = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    if custom_white_list:
        s.white |= set(custom_white_list)
    if custom_black_list:
        s.black |= set(custom_black_list)
    try:
        yield
    finally:
        s.enabled, s.white, s.black, s.level, s.dtype = prev


autocast = auto_cast


def amp_guard(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1"):
    return auto_cast(enable, custom_white_list, custom_black_list, level)


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2 casts model params to the compute dtype.
    Master fp32 weights live in the optimizer state (multi_precision)."""
    d = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=d)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """reference: amp/grad_scaler.py GradScaler + loss_scaler.py
    (check_finite_and_unscale + update_loss_scaling ops, operators/amp/).

    With bf16 (the TPU default) scaling is mathematically unnecessary;
    `enable=False` semantics. The dynamic-scaling state machine is kept fully
    functional for fp16 parity."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = set()   # optimizers already unscaled this step

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        """Idempotent per step: a second call before step() is a no-op, so
        the unscale → clip → step pattern doesn't divide twice."""
        if not self._enable or id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        self._found_inf = False
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = p.grad._value * inv
                if not bool(jnp.all(jnp.isfinite(g))):
                    self._found_inf = True
                p.grad._value = g

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        self._unscaled.discard(id(optimizer))
        if not self._found_inf:
            optimizer.step()
        self._update()

    def minimize(self, optimizer, scaled_loss):
        """Paddle contract: the caller has already run
        ``scaled_loss.backward()``; minimize only unscales and steps
        (reference: amp/grad_scaler.py minimize)."""
        self.step(optimizer)

    def update(self):
        pass  # state already updated in step(); kept for API parity

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]
