"""Python face of the native (C++) data engine.

``NativeArrayLoader`` drives native/src/data_engine.cc over ctypes: the
shuffle, shard, gather and staging copies all happen on C++ threads with
the GIL released, overlapping host data prep with device compute — the
role DataFeed worker threads + BufferedReader played in the reference
(SURVEY.md §2 N21/N34). ``token_windows`` exposes the strided-row trick:
a flat token corpus (e.g. np.memmap of an int32 file) becomes a dataset
of OVERLAPPING [seq_len+1] windows without materializing them — the GPT
pretraining input pipeline.
"""
from __future__ import annotations

import ctypes
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from ..core import native as _native


class NativeArrayLoader:
    """Iterate [batch, ...] numpy batches gathered by the C++ engine.

    arrays: same-length-dim0 C-contiguous numpy arrays (one per field).
    zero_copy: yield views into the engine's staging slots (valid until
    ``prefetch_depth - 1`` further batches have been drawn) instead of
    copies. Default False: yield owned copies.
    """

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False, num_shards: int = 1,
                 shard_id: int = 0, prefetch_depth: int = 4,
                 num_workers: int = 2, epochs: int = 1,
                 zero_copy: bool = False,
                 row_bytes: Optional[List[int]] = None,
                 strides: Optional[List[int]] = None,
                 n_samples: Optional[int] = None,
                 out_shapes: Optional[List[tuple]] = None):
        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._arrays = [np.ascontiguousarray(a) if strides is None else a
                        for a in arrays]
        n = len(self._arrays)
        if n_samples is None:
            n_samples = len(self._arrays[0])
            for a in self._arrays:
                if len(a) != n_samples:
                    raise ValueError("arrays disagree on dim0")
        self.n_samples = int(n_samples)
        bases = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value
              for a in self._arrays])
        if row_bytes is None:
            row_bytes = [int(np.prod(a.shape[1:], dtype=np.int64) *
                             a.itemsize) for a in self._arrays]
        if strides is None:
            strides = list(row_bytes)
        self._row_bytes = row_bytes
        rb = (ctypes.c_int64 * n)(*row_bytes)
        st = (ctypes.c_int64 * n)(*strides)
        if out_shapes is None:
            out_shapes = [tuple(a.shape[1:]) for a in self._arrays]
        self._out_shapes = out_shapes
        self._dtypes = [a.dtype for a in self._arrays]
        self.batch_size = int(batch_size)
        self._zero_copy = zero_copy
        self._depth = max(2, int(prefetch_depth))
        self._h = lib.ptl_loader_create(
            n, bases, st, rb, self.n_samples, self.batch_size,
            int(bool(shuffle)), ctypes.c_uint64(seed & (2**64 - 1)),
            int(bool(drop_last)), int(num_shards), int(shard_id),
            self._depth, int(num_workers), int(epochs))
        if not self._h:
            raise RuntimeError("native loader creation failed")
        self._held: deque = deque()
        self._n_arrays = n

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None:
            raise StopIteration
        ptrs = (ctypes.c_void_p * self._n_arrays)()
        rows = ctypes.c_int64(0)
        slot = self._lib.ptl_loader_next(self._h, ptrs, ctypes.byref(rows))
        if slot < 0:
            raise StopIteration
        out = []
        for i in range(self._n_arrays):
            nbytes = int(rows.value) * self._row_bytes[i]
            buf = (ctypes.c_char * nbytes).from_address(ptrs[i])
            view = np.frombuffer(buf, dtype=self._dtypes[i]).reshape(
                (int(rows.value),) + tuple(self._out_shapes[i]))
            out.append(view if self._zero_copy else view.copy())
        if self._zero_copy:
            self._held.append(slot)
            # keep the most recent depth-1 slots alive for the consumer
            while len(self._held) > self._depth - 1:
                self._lib.ptl_loader_release(self._h, self._held.popleft())
        else:
            self._lib.ptl_loader_release(self._h, slot)
        return tuple(out)

    def close(self):
        if self._h is not None:
            h, self._h = self._h, None
            self._lib.ptl_loader_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def token_windows(tokens: np.ndarray, seq_len: int, batch_size: int,
                  stride: Optional[int] = None, shuffle: bool = True,
                  seed: int = 0, drop_last: bool = True,
                  num_shards: int = 1, shard_id: int = 0,
                  epochs: int = 1, **kw) -> NativeArrayLoader:
    """Loader of [batch, seq_len + 1] windows over a flat token array
    (labels are the shifted window; +1 covers both). ``tokens`` may be an
    np.memmap over a binary corpus file — windows are gathered straight
    from the mapping, never materialized."""
    tokens = np.ascontiguousarray(tokens).reshape(-1)
    if stride is None:
        stride = seq_len
    span = seq_len + 1
    if len(tokens) < span:
        raise ValueError("token stream shorter than one window")
    n = (len(tokens) - span) // stride + 1
    it = tokens.itemsize
    return NativeArrayLoader(
        [tokens], batch_size, shuffle=shuffle, seed=seed,
        drop_last=drop_last, num_shards=num_shards, shard_id=shard_id,
        epochs=epochs, row_bytes=[span * it], strides=[stride * it],
        n_samples=n, out_shapes=[(span,)], **kw)
