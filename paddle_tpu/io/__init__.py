"""Data pipeline (reference: python/paddle/io/ — Dataset/DataLoader,
python/paddle/fluid/reader.py:149; C++ side framework/data_feed.cc and
operators/reader/buffered_reader).

TPU-native: the loader is a host-side prefetch pipeline (worker threads +
bounded queue, double-buffering batches to device) — the reference's
BufferedReader GPU-prefetch idea without per-op readers. A C++ acceleration
for hot collate paths lives in csrc/ (optional, ctypes-loaded).
"""
from __future__ import annotations

import itertools
import queue
import threading
import weakref
from typing import Iterable, List, Optional

import numpy as np

from ..core import rng
from ..framework.tensor import Tensor


class Dataset:
    """Map-style dataset (reference: python/paddle/io/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


def synthetic_optin(cls_name: str, synthetic_size, default: int) -> int:
    """Synthetic data is OPT-IN across every dataset family (round-3
    policy: a typo'd path must not silently train on fake data).
    Without a real data file, callers must pass synthetic_size=N
    explicitly to acknowledge the corpus is synthetic."""
    if synthetic_size is None:
        raise ValueError(
            f"{cls_name}: no data_file was given and downloading is not "
            "possible here. Pass data_file=<path to the real dataset "
            "archive>, or explicitly opt in to a deterministic FAKE "
            f"corpus with synthetic_size=N (e.g. {default}) for "
            "tests/smoke runs.")
    return int(synthetic_size)


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: List):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    """Zip same-length datasets: sample i concatenates the fields of
    every dataset's sample i (reference: fluid/dataloader/dataset.py
    ComposeDataset)."""

    def __init__(self, datasets):
        if not datasets:
            raise ValueError("datasets cannot be empty")
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        for d in self.datasets[1:]:
            if len(d) != n:
                raise ValueError("ComposeDataset datasets must share "
                                 "one length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            s = d[idx]
            out.extend(s if isinstance(s, (tuple, list)) else [s])
        return tuple(out)


def random_split(dataset, lengths, generator=None):
    idx = rng._numpy_generator.permutation(len(dataset))
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, idx[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


# -- samplers ---------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(rng._numpy_generator.randint(
                0, n, self.num_samples).tolist())
        return iter(rng._numpy_generator.permutation(n)[
            :self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(rng._numpy_generator.choice(
            len(self.weights), self.num_samples, self.replacement,
            p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: python/paddle/io/batch_sampler.py"""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = bool(shuffle)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler — shards the index space across data-parallel
    ranks; on TPU the 'rank' is the process index of the jax runtime."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            r = np.random.RandomState(self.epoch)
            indices = r.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# -- collate ----------------------------------------------------------------
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class WorkerInfo:
    """Per-worker context visible inside an IterableDataset.__iter__
    (reference: fluid/reader.py worker loop sets a module-global
    _worker_info; public API paddle.io.get_worker_info). A
    sharding-aware iterable dataset reads ``id``/``num_workers`` and
    yields only its split; a naive dataset iterated by N workers yields
    every sample N times — same contract as the reference."""

    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_tls = threading.local()


def get_worker_info():
    """Inside a DataLoader worker: that worker's WorkerInfo; None in the
    main thread (reference: paddle.io.get_worker_info)."""
    return getattr(_worker_tls, "info", None)


class _PoolState:
    """Shared state of a DataLoader worker pool. Lives OUTSIDE the
    iterator so worker threads never hold a strong reference to it —
    otherwise an abandoned iterator could never be garbage-collected
    (threads are GC roots) and its pool would leak forever."""

    END = object()

    def __init__(self, nw, prefetch):
        self.nw = nw
        self.stop = threading.Event()
        self.cond = threading.Condition()
        self.results = {}
        self.dispatched = 0
        self.dispatch_done = False
        # iterable mode: per-worker produced-batch counts, recorded when
        # each worker's stream ends. Worker w's k-th batch is published at
        # seq k*nw + w (round-robin interleave — deterministic delivery
        # order); the consumer skips seqs that can never arrive.
        self.worker_counts = {}
        self.inflight = threading.Semaphore(prefetch * nw)
        # iterable mode: per-worker backpressure. A shared semaphore
        # would deadlock: a fast worker could hold every permit while the
        # consumer waits (in round-robin order) on a slow worker that is
        # itself parked in acquire().
        self.worker_sems = [threading.Semaphore(prefetch)
                            for _ in range(nw)]
        self.work_q = queue.Queue()
        # iterable mode: worker 0 probes whether the dataset is its own
        # iterator (shared cursor) and publishes the verdict here; the
        # other workers wait on the event before touching the dataset.
        self.probe_event = threading.Event()
        self.probe_single_stream = False

    def publish(self, seq, item):
        with self.cond:
            self.results[seq] = item
            self.cond.notify_all()

    def finish_dispatch(self, count):
        with self.cond:
            self.dispatched = count
            self.dispatch_done = True
            self.cond.notify_all()
        for _ in range(self.nw):
            self.work_q.put((None, self.END))

    def finish_worker(self, wid, count):
        with self.cond:
            self.worker_counts[wid] = count
            if len(self.worker_counts) == self.nw:
                self.dispatch_done = True
            self.cond.notify_all()

    def shutdown(self):
        """Idempotent: unblock the dispatcher (parked in acquire) and the
        workers (parked in get) so every pool thread exits."""
        if self.stop.is_set():
            return
        self.stop.set()
        for _ in range(self.nw + 1):
            self.inflight.release()
        for sem in self.worker_sems:
            sem.release()
        for _ in range(self.nw):
            self.work_q.put((None, self.END))
        self.probe_event.set()           # unblock workers awaiting probe
        with self.cond:
            self.cond.notify_all()


def _pool_dispatch(state, index_iter):
    seq = 0
    try:
        for indices in index_iter:
            state.inflight.acquire()
            if state.stop.is_set():
                break
            state.work_q.put((seq, indices))
            seq += 1
    finally:
        state.finish_dispatch(seq)


def _pool_map_worker(state, dataset, collate_fn):
    while not state.stop.is_set():
        seq, indices = state.work_q.get()
        if indices is state.END:
            break
        try:
            state.publish(seq, collate_fn([dataset[i] for i in indices]))
        except BaseException as e:       # re-raised in the consumer
            state.publish(seq, e)


def _pool_iterable_worker(state, dataset, collate_fn, batch_size,
                          drop_last, wid):
    """One of nw streams over an IterableDataset. Exposes WorkerInfo so
    sharding-aware datasets yield their split (reference
    fluid/reader.py:91 worker semantics); publishes its k-th batch at
    seq k*nw + wid."""
    _worker_tls.info = WorkerInfo(wid, state.nw, dataset)
    k = 0
    try:
        # A dataset that is its own iterator (iter(ds) returns ds) holds
        # ONE shared cursor, which N threads cannot drive safely (a
        # generator would raise "already executing"; a stateful __next__
        # would lose samples) — and such datasets often RESET the cursor
        # in __iter__, so a late worker merely *calling* iter() would
        # clobber worker 0's in-progress iteration. Probe exactly once:
        # worker 0 calls iter() and publishes the verdict via an Event;
        # workers 1..N-1 wait for it and bail out (single-stream
        # fallback) when the dataset is a self-iterator. Datasets whose
        # __iter__ returns fresh independent iterators keep the full
        # N-stream parallelism.
        if wid == 0:
            state.probe_single_stream = True   # pessimistic until probed
            try:
                it = iter(dataset)
                state.probe_single_stream = it is dataset
            finally:
                state.probe_event.set()
        else:
            state.probe_event.wait()
            if state.probe_single_stream or state.stop.is_set():
                return
            it = iter(dataset)
        while not state.stop.is_set():
            # draw via next(): islice would call iter(it) per batch,
            # re-triggering a cursor-resetting __iter__ every batch
            batch = []
            try:
                while len(batch) < batch_size:
                    batch.append(next(it))
            except StopIteration:
                pass
            if not batch or (drop_last and len(batch) < batch_size):
                break
            state.worker_sems[wid].acquire()
            if state.stop.is_set():
                break
            state.publish(k * state.nw + wid, collate_fn(batch))
            k += 1
    except BaseException as e:
        state.publish(k * state.nw + wid, e)
        k += 1
    finally:
        state.finish_worker(wid, k)


class _DataLoaderIter:
    """num_workers > 0: a POOL of num_workers loader threads (the
    reference runs N worker processes, fluid/reader.py:91; threads here —
    numpy/host IO releases the GIL, and jax arrays are not fork-safe).
    Batches are delivered IN ORDER via per-batch sequence numbers and a
    reorder buffer, with at most prefetch_factor×workers in flight.
    Iterable datasets run num_workers independent streams: each worker
    iterates its own iter(dataset) with WorkerInfo exposed via
    get_worker_info() (reference fluid/reader.py:91 per-worker-process
    semantics) — sharding-aware datasets yield their split, and batches
    interleave round-robin deterministically. Threads reference only the
    _PoolState; a weakref.finalize shuts the pool down when the iterator
    is dropped (early break / exception) so no thread ever leaks."""

    def __init__(self, loader):
        self.loader = loader
        self._index_iter = iter(loader.batch_sampler) \
            if not loader._iterable_mode else None
        self._state = None
        self._next_seq = 0
        if loader.num_workers > 0:
            nw = loader.num_workers
            st = _PoolState(nw, max(2, loader.prefetch_factor))
            self._state = st
            self._finalizer = weakref.finalize(self, _PoolState.shutdown,
                                               st)
            if loader._iterable_mode:
                threads = [threading.Thread(
                    target=_pool_iterable_worker,
                    args=(st, loader.dataset, loader.collate_fn,
                          loader.batch_size, loader.drop_last, w),
                    daemon=True) for w in range(nw)]
            else:
                threads = [threading.Thread(
                    target=_pool_map_worker,
                    args=(st, loader.dataset, loader.collate_fn),
                    daemon=True) for _ in range(nw)]
                threads.append(threading.Thread(
                    target=_pool_dispatch, args=(st, self._index_iter),
                    daemon=True))
            for t in threads:
                t.start()

    def _load_batch(self, indices):
        samples = [self.loader.dataset[i] for i in indices]
        return self.loader.collate_fn(samples)

    def __next__(self):
        st = self._state
        iterable = self.loader._iterable_mode
        if st is not None:
            with st.cond:
                while True:
                    if self._next_seq in st.results:
                        item = st.results.pop(self._next_seq)
                        self._next_seq += 1
                        break
                    if iterable:
                        # worker streams end at different k's: skip seqs a
                        # finished worker can never publish; stop when
                        # every worker is done and no published seq is
                        # left at/after next_seq
                        w = self._next_seq % st.nw
                        k = self._next_seq // st.nw
                        if w in st.worker_counts and \
                                k >= st.worker_counts[w]:
                            if len(st.worker_counts) == st.nw and not any(
                                    s >= self._next_seq
                                    for s in st.results):
                                raise StopIteration
                            self._next_seq += 1
                            continue
                    elif st.dispatch_done and \
                            self._next_seq >= st.dispatched:
                        raise StopIteration
                    st.cond.wait()
            if iterable:
                st.worker_sems[(self._next_seq - 1) % st.nw].release()
            else:
                st.inflight.release()
            if isinstance(item, BaseException):
                st.shutdown()
                raise item
            return item
        if self.loader._iterable_mode:
            if not hasattr(self, "_raw_iter"):
                self._raw_iter = iter(self.loader.dataset)
            # draw via next(): islice would call iter() on the stream per
            # batch, restarting datasets whose __iter__ resets a shared
            # cursor (same hazard as the worker-pool path)
            batch = []
            try:
                while len(batch) < self.loader.batch_size:
                    batch.append(next(self._raw_iter))
            except StopIteration:
                pass
            if not batch or (self.loader.drop_last and
                             len(batch) < self.loader.batch_size):
                raise StopIteration
            return self.loader.collate_fn(batch)
        return self._load_batch(next(self._index_iter))

    def close(self):
        if self._state is not None:
            self._state.shutdown()

    def __iter__(self):
        return self


class _NativeIterAdapter:
    """Adapts NativeArrayLoader output (numpy tuples) to the DataLoader
    contract (tuples of Tensors)."""

    def __init__(self, nat):
        self._nat = nat

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self._nat)
        except StopIteration:
            self._nat.close()
            raise
        return tuple(Tensor(b) for b in batch)


class DataLoader:
    """reference: fluid/reader.py DataLoader(:149). Thread-prefetch instead of
    the reference's multiprocess+mmap pipeline (jax arrays are not fork-safe;
    worker threads release the GIL during numpy/host IO). Array-backed
    datasets are served by the native C++ engine (io/native_engine.py)
    when its semantics match; ``use_native_engine=False`` opts out.

    Native-engine behavior differences (vs the Python ``num_workers=0``
    path): under ``shuffle=True`` the engine draws its own mt19937_64
    Fisher-Yates permutation from ``paddle.seed``, which is a *different*
    order than the Python ``RandomSampler`` for the same seed (both are
    seed-deterministic); and batches are prefetched by C++ threads reading
    the source arrays asynchronously, so the dataset's arrays must not be
    mutated in place while iterating."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 use_native_engine=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.collate_fn = collate_fn or default_collate_fn
        self.use_native_engine = use_native_engine
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if not self._iterable_mode:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
        else:
            self.batch_sampler = None

    def __iter__(self):
        it = self._try_native_iter()
        return it if it is not None else _DataLoaderIter(self)

    def _try_native_iter(self):
        """Use the C++ data engine (core/native.py + native/) when the
        configuration maps onto it exactly: an array-backed dataset,
        default collate, plain (Random|Sequence)-sampled BatchSampler.
        Anything else falls back to the Python path."""
        if self.use_native_engine is False:
            return None
        if self._iterable_mode or self.collate_fn is not default_collate_fn:
            return None
        bs = self.batch_sampler
        if type(bs) is not BatchSampler or \
                type(bs.sampler) not in (RandomSampler, SequenceSampler):
            return None
        if isinstance(bs.sampler, RandomSampler) and (
                bs.sampler.replacement or bs.sampler._num_samples):
            return None
        if type(self.dataset) is not TensorDataset:
            return None
        try:
            from ..core import native as _native

            if not _native.available():
                return None
            from .native_engine import NativeArrayLoader

            arrays = [np.asarray(t._value) if isinstance(t, Tensor)
                      else np.asarray(t) for t in self.dataset.tensors]
            # only plain fixed-size buffer dtypes can be byte-gathered
            if any(a.dtype.hasobject or a.dtype.kind not in "biufc"
                   for a in arrays):
                return None
            # the sampler object, not the stored kwarg, decides the order
            # (an explicitly passed RandomSampler means shuffle)
            shuffle = isinstance(bs.sampler, RandomSampler)
            seed = int(rng._numpy_generator.randint(0, 2**31 - 1))
            nat = NativeArrayLoader(
                arrays, bs.batch_size, shuffle=shuffle, seed=seed,
                drop_last=bs.drop_last,
                prefetch_depth=max(2, self.prefetch_factor),
                num_workers=max(1, self.num_workers), epochs=1)
        except Exception:
            return None
        return _NativeIterAdapter(nat)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no fixed length")
        return len(self.batch_sampler)
