"""paddle.nn equivalent — layers, functional, initializers."""
from ..framework.param_attr import ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.activation import (CELU, ELU, GELU, SELU, Hardshrink,  # noqa: F401
                               Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
                               LogSigmoid, LogSoftmax, Maxout, Mish, PReLU,
                               ReLU, ReLU6, Sigmoid, Silu, Softmax, Softplus,
                               Softshrink, Softsign, Swish, Tanh, Tanhshrink,
                               ThresholdedReLU)
from . import utils  # noqa: F401
from .layer.decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer.common import (AlphaDropout, Bilinear, CosineSimilarity,  # noqa: F401
                           PairwiseDistance,
                           Dropout, Dropout2D, Dropout3D, Embedding, Flatten,
                           Identity, Linear, Pad1D, Pad2D, Pad3D,
                           PixelShuffle, Fold, Unfold, Upsample,
                           UpsamplingBilinear2D, UpsamplingNearest2D,
                           ZeroPad2D)
from .layer.container import (LayerDict, LayerList, ParameterList,  # noqa: F401
                              Sequential)
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D,  # noqa: F401
                         Conv2DTranspose, Conv3D, Conv3DTranspose)
from .layer.layers import Layer  # noqa: F401
from .layer.loss import (BCELoss, BCEWithLogitsLoss,  # noqa: F401
                         CosineEmbeddingLoss, CrossEntropyLoss, CTCLoss,
                         HingeEmbeddingLoss, HSigmoidLoss, KLDivLoss, L1Loss,
                         MarginRankingLoss, MSELoss, NLLLoss, SmoothL1Loss,
                         TripletMarginLoss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D,  # noqa: F401
                         BatchNorm3D, GroupNorm, InstanceNorm1D,
                         InstanceNorm2D, InstanceNorm3D, LayerNorm,
                         LocalResponseNorm, RMSNorm, SpectralNorm,
                         SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,  # noqa: F401
                            AdaptiveAvgPool3D, AdaptiveMaxPool1D,
                            AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
                            AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                            MaxPool3D)
from .layer.rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell,  # noqa: F401
                        RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,  # noqa: F401
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    from ..optimizer.clip import clip_grad_norm_ as _impl

    return _impl(parameters, max_norm, norm_type, error_if_nonfinite)


class ClipGradByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


class ClipGradByGlobalNorm:
    """reference: fluid/clip.py GradientClipByGlobalNorm."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name


class ClipGradByValue:
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min
