"""paddle.nn.functional equivalent."""
from ...tensor.creation import one_hot  # noqa: F401
from ...tensor.manipulation import gather, gather_nd, squeeze, unsqueeze  # noqa: F401
from .activation import (celu, elu, gelu, gumbel_softmax, hardshrink,  # noqa: F401
                         hardsigmoid, hardswish, hardtanh, leaky_relu,
                         log_sigmoid, log_softmax, maxout, mish, prelu, relu,
                         relu6, selu, sigmoid, silu, softmax, softplus,
                         softshrink, softsign, swish, tanh, tanhshrink,
                         thresholded_relu, glu, relu_, elu_, softmax_,
                         tanh_)
from .attention import scaled_dot_product_attention  # noqa: F401
from ...ops.fused_ce import fused_linear_cross_entropy  # noqa: F401
from .common import (alpha_dropout, bilinear, cosine_similarity,  # noqa: F401
                     dropout, dropout2d, dropout3d, embedding, fold,
                     interpolate, label_smooth, linear, pad, pixel_shuffle,
                     unfold, upsample, zeropad2d)
from .conv import (conv1d, conv1d_transpose, conv2d, conv2d_transpose,  # noqa: F401
                   conv3d, conv3d_transpose)
from .loss import (binary_cross_entropy, binary_cross_entropy_with_logits,  # noqa: F401
                   cosine_embedding_loss, cross_entropy, ctc_loss,
                   hinge_embedding_loss, kl_div, l1_loss, log_loss,
                   margin_ranking_loss, mse_loss, nll_loss, sigmoid_focal_loss,
                   smooth_l1_loss, softmax_with_cross_entropy,
                   square_error_cost, triplet_margin_loss)
from .norm import (batch_norm, group_norm, instance_norm, layer_norm,  # noqa: F401
                   local_response_norm, normalize, rms_norm)
from .vision import (affine_grid, grid_sample, temporal_shift,  # noqa: F401
                     deform_conv2d)
from . import extension  # noqa: F401
from .extension import diag_embed, edit_distance, gather_tree  # noqa: F401
from . import sequence_lod  # noqa: F401
from .sequence_lod import (sequence_mask, sequence_pad, sequence_unpad,  # noqa: F401
                           sequence_pool, sequence_first_step,
                           sequence_last_step, sequence_expand,
                           sequence_expand_as, sequence_concat,
                           sequence_softmax, sequence_reverse, sequence_conv,
                           sequence_enumerate, sequence_slice,
                           sequence_erase, sequence_reshape,
                           sequence_scatter, sequence_topk_avg_pooling)
from . import crf  # noqa: F401
from .crf import chunk_eval, crf_decoding, linear_chain_crf  # noqa: F401
from . import misc_ops  # noqa: F401
from .misc_ops import (nce, sample_logits, row_conv, data_norm,  # noqa: F401
                       shuffle_channel, rank_loss, center_loss,
                       im2sequence, lod_reset, pad_constant_like,
                       unique_with_counts, partial_concat, partial_sum,
                       match_matrix_tensor, var_conv_2d)
from .loss import dice_loss, hsigmoid_loss, npair_loss  # noqa: F401
from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d,  # noqa: F401
                      adaptive_avg_pool3d, adaptive_max_pool3d,
                      adaptive_max_pool1d, adaptive_max_pool2d, avg_pool1d,
                      avg_pool2d, avg_pool3d, max_pool1d, max_pool2d,
                      max_pool3d)
