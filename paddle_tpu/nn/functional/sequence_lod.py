"""Sequence (LoD) op family, dense-ragged form.

Reference: paddle/fluid/operators/sequence_ops/ (~15 ops) with python
surface python/paddle/fluid/layers/sequence_lod.py. The reference carries
raggedness in LoDTensor offsets; this framework's stance is "LoD => dense
ragged at the data layer": every op here takes an explicit ``lengths``
tensor (the LoD level-0 run lengths) next to either

  * a *packed* tensor ``[sum(lengths), ...]`` (rows of all sequences
    concatenated — the reference's LoDTensor buffer layout), or
  * a *padded* tensor ``[batch, max_time, ...]``.

Padded-form ops are jittable (static shapes, masks instead of offsets —
the TPU-friendly formulation); ops whose *output* row count is
data-dependent (sequence_unpad, sequence_expand, sequence_erase) execute
eagerly on host, like the reference's CPU kernels for the same ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...tensor._helper import apply, unwrap

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
    "sequence_expand_as", "sequence_concat", "sequence_softmax",
    "sequence_reverse", "sequence_conv", "sequence_enumerate",
    "sequence_slice", "sequence_erase", "sequence_reshape",
    "sequence_scatter", "sequence_topk_avg_pooling",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths -> [..., maxlen] 0/1 mask (reference:
    sequence_ops/sequence_mask_op.cc; public paddle.nn.functional API)."""
    from ...core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)
    lengths = unwrap(x)
    if maxlen is None:
        maxlen = int(np.asarray(lengths).max())
    maxlen = int(maxlen)

    def f(lv):
        t = jnp.arange(maxlen, dtype=lv.dtype)
        return (t < lv[..., None]).astype(d)

    return apply(f, x, differentiable=False, name="sequence_mask")


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Packed [sum(len), ...] + lengths -> (padded [B, maxlen, ...],
    lengths) (reference: sequence_ops/sequence_pad_op.cc). Jittable: the
    gather index grid is computed from cumulative offsets; out-of-range
    positions read row 0 and are overwritten by ``pad_value``."""
    if length is None:
        raise ValueError(
            "sequence_pad: dense-ragged form requires the explicit "
            "`length` tensor (the LoD run lengths).")
    if maxlen is not None:
        # Static maxlen: no host materialization of lengths — the op
        # stages under jit even when `length` is a traced value.
        ml = int(maxlen)
        lengths_out = length if isinstance(length, Tensor) else Tensor(
            jnp.asarray(unwrap(length)).reshape(-1))
    else:
        lengths_np = np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
        ml = int(lengths_np.max())
        lengths_out = Tensor(jnp.asarray(lengths_np))

    def f(v, lv, pv):
        lv = lv.reshape(-1)
        offs = jnp.concatenate([jnp.zeros((1,), lv.dtype),
                                jnp.cumsum(lv)[:-1]])
        t = jnp.arange(ml, dtype=lv.dtype)
        idx = offs[:, None] + t[None, :]               # [B, ml]
        valid = t[None, :] < lv[:, None]
        idx = jnp.where(valid, idx, 0)
        out = v[idx.reshape(-1)].reshape((lv.shape[0], ml) + v.shape[1:])
        mask = valid.reshape(valid.shape + (1,) * (v.ndim - 1))
        pad = jnp.asarray(pv, v.dtype)
        return jnp.where(mask, out, pad)

    out = apply(f, x, length, pad_value, name="sequence_pad")
    return out, lengths_out


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] + lengths -> packed [sum(len), ...] (reference:
    sequence_ops/sequence_unpad_op.cc). Output row count is data-dependent
    => eager host op."""
    v = np.asarray(unwrap(x))
    lens = np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
    rows = [v[b, :int(n)] for b, n in enumerate(lens)]
    return Tensor(jnp.asarray(np.concatenate(rows, axis=0)))


def _masked(v, lv, fill):
    t = jnp.arange(v.shape[1])
    mask = t[None, :] < lv.reshape(-1)[:, None]
    mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
    return jnp.where(mask, v, jnp.asarray(fill, v.dtype)), mask


def sequence_pool(input, pool_type, length=None, pad_value=0.0, name=None):  # noqa: A002
    """Masked pooling over time of a padded [B, T, ...] tensor (reference:
    sequence_ops/sequence_pool_op.cc — AVERAGE/SUM/SQRT/MAX/LAST/FIRST).
    Empty sequences yield ``pad_value`` like the reference."""
    if length is None:
        raise ValueError("sequence_pool: `length` is required")
    pt = pool_type.lower()

    def f(v, lv):
        lv = lv.reshape(-1)
        n = jnp.maximum(lv, 1).astype(v.dtype)
        n = n.reshape((-1,) + (1,) * (v.ndim - 2))
        if pt == "max":
            mv, _ = _masked(v, lv, -jnp.inf)
            out = mv.max(axis=1)
        elif pt in ("average", "sum", "sqrt"):
            mv, _ = _masked(v, lv, 0)
            out = mv.sum(axis=1)
            if pt == "average":
                out = out / n
            elif pt == "sqrt":
                out = out / jnp.sqrt(n)
        elif pt == "first":
            out = v[:, 0]
        elif pt == "last":
            idx = jnp.maximum(lv - 1, 0)
            out = jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1
            ).squeeze(1)
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        empty = (lv == 0).reshape((-1,) + (1,) * (v.ndim - 2))
        return jnp.where(empty, jnp.asarray(pad_value, v.dtype), out)

    return apply(f, input, length, name="sequence_pool")


def sequence_first_step(input, length=None, name=None):  # noqa: A002
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None, name=None):  # noqa: A002
    return sequence_pool(input, "last", length=length)


def sequence_expand(x, y_length, ref_level=0, name=None):
    """Repeat row-blocks of ``x`` per ``y_length`` counts (reference:
    sequence_ops/sequence_expand_op.cc). Dense form: x is [B, ...] (one
    row per sequence) or packed with its own lengths == 1; output packs
    x's row b repeated y_length[b] times. Output row count is
    data-dependent => eager host op."""
    v = np.asarray(unwrap(x))
    counts = np.asarray(unwrap(y_length)).astype(np.int64).reshape(-1)
    out = np.repeat(v, counts, axis=0)
    return Tensor(jnp.asarray(out))


def sequence_expand_as(x, y, y_length=None, name=None):
    """sequence_expand with counts taken from ``y``'s lengths (reference:
    sequence_ops/sequence_expand_as_op.cc)."""
    if y_length is None:
        raise ValueError("sequence_expand_as: dense-ragged form requires "
                         "`y_length`")
    return sequence_expand(x, y_length)


def sequence_concat(input, lengths=None, name=None):  # noqa: A002
    """Concatenate ragged sequences time-wise (reference:
    sequence_ops/sequence_concat_op.cc): row b of the output is
    seq_b(x1) ++ seq_b(x2) ++ ... Inputs are padded [B, Ti, ...] with
    lengths[i] = [B]; output is padded [B, sum(Ti), ...] plus the summed
    lengths."""
    if lengths is None:
        raise ValueError("sequence_concat: `lengths` (one per input) "
                         "required")
    vs = [np.asarray(unwrap(t)) for t in input]
    ls = [np.asarray(unwrap(le)).astype(np.int64).reshape(-1)
          for le in lengths]
    b = vs[0].shape[0]
    total = sum(l_ for l_ in ls)
    ml = int(total.max())
    out = np.zeros((b, ml) + vs[0].shape[2:], vs[0].dtype)
    for row in range(b):
        pos = 0
        for v, l_ in zip(vs, ls):
            n = int(l_[row])
            out[row, pos:pos + n] = v[row, :n]
            pos += n
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(total))


def sequence_softmax(input, length=None, axis=1, name=None):  # noqa: A002
    """Per-sequence masked softmax over time (reference:
    sequence_ops/sequence_softmax_op.cc). Padded [B, T, ...]; positions
    beyond the length get probability 0."""
    if length is None:
        raise ValueError("sequence_softmax: `length` is required")

    def f(v, lv):
        mv, mask = _masked(v, lv, -jnp.inf)
        out = jax.nn.softmax(mv, axis=axis)
        return jnp.where(mask, out, 0.0)

    return apply(f, input, length, name="sequence_softmax")


def sequence_reverse(x, length=None, name=None):
    """Reverse the valid prefix of each row (reference:
    sequence_ops/sequence_reverse_op.cc). Padding stays in place."""
    if length is None:
        raise ValueError("sequence_reverse: `length` is required")

    def f(v, lv):
        lv = lv.reshape(-1)
        t = jnp.arange(v.shape[1])
        rev = lv[:, None] - 1 - t[None, :]
        idx = jnp.where(t[None, :] < lv[:, None], rev, t[None, :])
        return jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), axis=1)

    return apply(f, x, length, name="sequence_reverse")


def sequence_conv(input, weight, length=None, context_length=3,  # noqa: A002
                  context_start=None, bias=None, padding=True, name=None):
    """Context-window projection over time (reference:
    sequence_ops/sequence_conv_op.cc + math/context_project.h): each
    timestep concatenates ``context_length`` neighbouring frames (zeros
    beyond sequence boundaries — boundaries come from ``length``, not the
    pad buffer) and projects by ``weight`` [context_length*D, M]."""
    if length is None:
        raise ValueError("sequence_conv: `length` is required")
    cl = int(context_length)
    cs = -((cl - 1) // 2) if context_start is None else int(context_start)

    def f(v, w, lv, *rest):
        lv = lv.reshape(-1)
        bsz, tmax, d = v.shape
        mv, _ = _masked(v, lv, 0)
        t = jnp.arange(tmax)
        cols = []
        for k in range(cl):
            shift = cs + k
            src = t + shift
            ok = (src >= 0) & (src < lv[:, None])
            src_c = jnp.clip(src, 0, tmax - 1)
            g = jnp.take_along_axis(
                mv, jnp.broadcast_to(src_c[None, :], (bsz, tmax))[..., None],
                axis=1)
            cols.append(jnp.where(ok[..., None], g, 0))
        ctx = jnp.concatenate(cols, axis=-1)        # [B, T, cl*D]
        out = ctx @ w
        if rest:
            out = out + rest[0]
        valid = (t[None, :] < lv[:, None])[..., None]
        return jnp.where(valid, out, 0)

    args = (input, weight, length) + ((bias,) if bias is not None else ())
    return apply(f, *args, name="sequence_conv")


def sequence_enumerate(input, win_size, pad_value=0, length=None,  # noqa: A002
                       name=None):
    """Sliding windows of ids (reference:
    sequence_ops/sequence_enumerate_op.cc): [B, T] int -> [B, T, win]
    where window positions past each row's length fill ``pad_value``.
    Like every sibling op in this dense-ragged module, the per-row valid
    extent comes from the explicit ``length`` tensor; without it the full
    padded width is treated as valid."""
    def f(v, lv=None):
        bsz, tmax = v.shape
        t = jnp.arange(tmax)
        if lv is None:
            row_len = jnp.full((bsz,), tmax, t.dtype)
        else:
            row_len = lv.reshape(-1).astype(t.dtype)
        outs = []
        for k in range(int(win_size)):
            src = t + k
            ok = src[None, :] < row_len[:, None]       # per-row extent
            src_c = jnp.clip(src, 0, tmax - 1)
            g = v[:, src_c]
            outs.append(jnp.where(ok, g, pad_value))
        return jnp.stack(outs, axis=-1)

    args = (input,) if length is None else (input, length)
    return apply(f, *args, differentiable=False, name="sequence_enumerate")


def sequence_erase(x, tokens, length=None, name=None):
    """Remove every occurrence of ``tokens`` from each row (reference:
    sequence_ops/sequence_erase_op.cc). Padded [B, T] int + lengths ->
    (padded [B, T] zero-padded, new lengths). Output row lengths are
    data-dependent => eager host op."""
    v = np.asarray(unwrap(x))
    lens = (np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
            if length is not None
            else np.full((v.shape[0],), v.shape[1], np.int64))
    drop = set(int(t) for t in tokens)
    out = np.zeros_like(v)
    new_len = np.zeros_like(lens)
    for b in range(v.shape[0]):
        keep = [t for t in v[b, :lens[b]] if int(t) not in drop]
        out[b, :len(keep)] = keep
        new_len[b] = len(keep)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(new_len))


def sequence_reshape(x, new_dim, length=None, name=None):
    """Re-chunk each sequence's feature payload to ``new_dim`` columns
    (reference: sequence_ops/sequence_reshape_op.cc): packed
    [total, D] -> [total*D/new_dim, new_dim]; each row length scales by
    D/new_dim (must divide exactly, like the reference checks)."""
    v = unwrap(x)
    d = int(v.shape[-1])
    if (d * int(np.prod(v.shape[:-1]))) % int(new_dim):
        raise ValueError(
            f"sequence_reshape: total elements not divisible by "
            f"new_dim={new_dim}")
    out = apply(lambda vv: vv.reshape(-1, int(new_dim)), x,
                name="sequence_reshape")
    if length is None:
        return out
    lens = np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
    if (lens * d) .sum() % int(new_dim) or np.any((lens * d) % new_dim):
        raise ValueError("sequence_reshape: a row's payload is not "
                         "divisible by new_dim")
    return out, Tensor(jnp.asarray(lens * d // int(new_dim)))


def sequence_scatter(x, index, updates, updates_length=None, name=None):
    """Per-row scatter-ADD into the time axis (reference:
    sequence_ops/sequence_scatter_op.cc: Out[b][ids[b][j]] += upd[b][j]
    for each row's segment). Dense form: index/updates padded [B, K]
    with ``updates_length`` valid counts."""
    if updates_length is None:
        raise ValueError("sequence_scatter: `updates_length` is required")

    def f(xv, idx, upd, ul):
        ul = ul.reshape(-1)
        k = jnp.arange(idx.shape[1])
        valid = k[None, :] < ul[:, None]
        idx_c = jnp.clip(idx, 0, xv.shape[1] - 1)
        upd_m = jnp.where(valid, upd, 0).astype(xv.dtype)
        b = jnp.arange(xv.shape[0])[:, None]
        b = jnp.broadcast_to(b, idx.shape)
        return xv.at[b, idx_c].add(upd_m)

    return apply(f, x, index, updates, updates_length,
                 name="sequence_scatter")


def sequence_topk_avg_pooling(x, length=None, topks=(1,), name=None):
    """Average of the top-k time positions per feature (reference:
    sequence_ops/sequence_topk_avg_pooling_op.cc — text-matching
    pooling). Padded [B, T, C] + lengths -> [B, len(topks), C]; rows
    shorter than k average their full top-|row| prefix (reference
    zero-pads the tail of the sort)."""
    if length is None:
        raise ValueError("sequence_topk_avg_pooling: `length` required")
    topks = tuple(int(k) for k in topks)

    def f(v, lv):
        lv = lv.reshape(-1)
        mv, _ = _masked(v, lv, -jnp.inf)
        srt = jnp.sort(mv, axis=1)[:, ::-1]          # [B, T, C] desc
        outs = []
        for k in topks:
            kk = min(k, v.shape[1])
            top = srt[:, :kk]
            # positions beyond the row length carry -inf: mask to 0 and
            # divide by the true count min(k, len)
            cnt = jnp.minimum(lv, kk).astype(v.dtype)
            top = jnp.where(jnp.isfinite(top), top, 0.0)
            outs.append(top.sum(axis=1) /
                        jnp.maximum(cnt, 1.0)[:, None])
        return jnp.stack(outs, axis=1)               # [B, n_topk, C]

    return apply(f, x, length, name="sequence_topk_avg_pooling")


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    """Per-row slice [offset[b], offset[b]+length[b]) of the time axis
    (reference: sequence_ops/sequence_slice_op.cc), returned padded to
    max(length) with zeros, plus the new lengths."""
    v = unwrap(input)
    off = np.asarray(unwrap(offset)).astype(np.int64).reshape(-1)
    ln = np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
    ml = int(ln.max())

    def f(vv):
        t = jnp.arange(ml)
        idx = jnp.asarray(off)[:, None] + t[None, :]
        ok = t[None, :] < jnp.asarray(ln)[:, None]
        idx = jnp.clip(idx, 0, vv.shape[1] - 1)
        out = jnp.take_along_axis(
            vv, idx.reshape(idx.shape + (1,) * (vv.ndim - 2)), axis=1)
        mask = ok.reshape(ok.shape + (1,) * (vv.ndim - 2))
        return jnp.where(mask, out, 0)

    out = apply(f, input, name="sequence_slice")
    return out, Tensor(jnp.asarray(ln))
