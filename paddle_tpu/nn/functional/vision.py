"""Spatial sampling ops: affine_grid + grid_sample.

TPU-native equivalents of the reference's spatial sampler pair
(reference: paddle/fluid/operators/affine_grid_op.cc,
operators/grid_sampler_op.cc + python/paddle/nn/functional/vision.py:60,152).
Everything is expressed as vectorized gathers over a flattened H*W axis —
no scalar loops, fully jittable and differentiable (the reference's CPU/GPU
kernels hand-roll the 4-corner interpolation and its backward; here jax AD
derives the scatter-add backward automatically).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor._helper import apply, unwrap

__all__ = ["affine_grid", "grid_sample"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a sampling grid [N, H, W, 2] from batched affine transforms
    ``theta`` [N, 2, 3] (reference: nn/functional/vision.py:60)."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().reshape(-1)]
    n, _, h, w = [int(v) for v in out_shape]

    def f(th):
        dt = th.dtype
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w, dtype=dt) if w > 1 else \
                jnp.zeros((1,), dt)
            ys = jnp.linspace(-1.0, 1.0, h, dtype=dt) if h > 1 else \
                jnp.zeros((1,), dt)
        else:
            xs = (2.0 * jnp.arange(w, dtype=dt) + 1.0) / w - 1.0
            ys = (2.0 * jnp.arange(h, dtype=dt) + 1.0) / h - 1.0
        gx, gy = jnp.meshgrid(xs, ys)                   # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)       # [H, W, 3]
        # [N, H, W, 2] = base @ theta^T per batch
        return jnp.einsum("hwk,njk->nhwj", base, th)

    return apply(f, theta, name="affine_grid")


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(x, lo, span):
    """Continuous reflection of x into [lo, lo+span] (grid_sampler_op.h
    reflection semantics)."""
    if span <= 0:
        return jnp.zeros_like(x)
    d = jnp.abs(x - lo) % (2.0 * span)
    return lo + (span - jnp.abs(d - span))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N, C, H, W] at grid [N, Hg, Wg, 2] locations (normalized
    to [-1, 1]; grid[..., 0] indexes width, grid[..., 1] height).
    Reference: nn/functional/vision.py:152, operators/grid_sampler_op.cc."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest: {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode: {padding_mode}")

    def f(xv, gv):
        n, c, h, w = xv.shape
        gx = _unnormalize(gv[..., 0].astype(jnp.float32), w, align_corners)
        gy = _unnormalize(gv[..., 1].astype(jnp.float32), h, align_corners)

        if padding_mode == "reflection":
            if align_corners:
                gx = _reflect(gx, 0.0, float(w - 1))
                gy = _reflect(gy, 0.0, float(h - 1))
            else:
                gx = jnp.clip(_reflect(gx, -0.5, float(w)), 0, w - 1)
                gy = jnp.clip(_reflect(gy, -0.5, float(h)), 0, h - 1)
        elif padding_mode == "border":
            gx = jnp.clip(gx, 0, w - 1)
            gy = jnp.clip(gy, 0, h - 1)

        xf = xv.reshape(n, c, h * w)

        def gather(iy, ix):
            """xf values at integer (iy, ix) with zero outside."""
            inb = ((iy >= 0) & (iy < h) & (ix >= 0) & (ix < w))
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            idx = (iyc * w + ixc).reshape(n, 1, -1)      # [N, 1, Hg*Wg]
            vals = jnp.take_along_axis(
                xf, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
            vals = vals.reshape((n, c) + gv.shape[1:3])
            return vals * inb[:, None].astype(xv.dtype)

        if mode == "nearest":
            ix = jnp.round(gx).astype(jnp.int32)
            iy = jnp.round(gy).astype(jnp.int32)
            return gather(iy, ix)

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0).astype(xv.dtype)
        wy = (gy - y0).astype(xv.dtype)
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        wx = wx[:, None]
        wy = wy[:, None]
        return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy + v11 * wx * wy)

    return apply(f, x, grid, name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """Shift a fraction of channels across the time axis of a [N*T, C, H,
    W] clip batch (TSM; reference: operators/temporal_shift_op.cc +
    fluid/layers/nn.py:13337). The first ``C*ratio`` channels read from
    t-1, the next ``C*ratio`` from t+1, the rest stay — expressed as two
    static pads+slices over the folded [N, T, C, H, W] view (XLA fuses
    them; the zero boundary frames fall out of the pad)."""
    if not isinstance(seg_num, int):
        raise TypeError("seg_num must be int type.")

    def f(xv):
        nt, c, h, w = xv.shape
        n = nt // seg_num
        v = xv.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(v, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        back = pad[:, :seg_num, :c1]               # channel k ← t-1
        fwd = pad[:, 2:, c1:c2]                    # channel k ← t+1
        keep = v[:, :, c2:]
        return jnp.concatenate([back, fwd, keep], axis=2) \
            .reshape(nt, c, h, w)

    return apply(f, x, name="temporal_shift")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, groups=1, mask=None, name=None):
    """Deformable convolution v1 (mask=None) / v2 (reference:
    operators/deformable_conv_op.cc, python/paddle/vision/ops.py:394).

    The reference's CUDA kernel im2col-gathers per sampling location;
    here the K=kh*kw learned-offset taps are bilinearly sampled as one
    vectorized gather producing [N, Cin, K, Ho, Wo], and the conv
    reduces to a single einsum against [Cout, Cin/g, K] — MXU-friendly,
    and jax AD derives the scatter-add backward for x/offset/mask."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def f(xv, off, wv, *rest):
        i = 0
        mv = bv = None
        if mask is not None:
            mv = rest[i]; i += 1
        if bias is not None:
            bv = rest[i]
        n, cin, h, w = xv.shape
        cout, cin_g, kh, kw = wv.shape
        k = kh * kw
        dg = off.shape[1] // (2 * k)                 # deformable groups
        ho = (h + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
        wo = (w + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1

        # base sampling grid per output position and tap: [K, Ho, Wo]
        oy = jnp.arange(ho) * s[0] - p[0]
        ox = jnp.arange(wo) * s[1] - p[1]
        ky = (jnp.arange(kh) * d[0])[:, None].repeat(kw, 1).reshape(k)
        kx = (jnp.arange(kw) * d[1])[None, :].repeat(kh, 0).reshape(k)
        base_y = oy[None, :, None] + ky[:, None, None]   # [K, Ho, 1]
        base_x = ox[None, None, :] + kx[:, None, None]   # [K, 1, Wo]

        # learned offsets: [N, dg, K, 2, Ho, Wo] (reference layout:
        # 2*dg*K channels ordered (dg, K, [y, x]))
        off = off.reshape(n, dg, k, 2, ho, wo)
        gy = base_y[None, None] + off[:, :, :, 0]        # [N, dg, K, Ho, Wo]
        gx = base_x[None, None] + off[:, :, :, 1]

        # bilinear sample x at (gy, gx) for every dg/tap: fold channels
        # into their deformable group
        xg = xv.reshape(n, dg, cin // dg, h * w)
        y0 = jnp.floor(gy); x0 = jnp.floor(gx)
        wy = (gy - y0).astype(xv.dtype)
        wx = (gx - x0).astype(xv.dtype)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)

        def at(iy, ix):
            inb = ((iy >= 0) & (iy < h) & (ix >= 0) & (ix < w))
            idx = (jnp.clip(iy, 0, h - 1) * w
                   + jnp.clip(ix, 0, w - 1))             # [N,dg,K,Ho,Wo]
            flat = idx.reshape(n, dg, 1, -1)
            vals = jnp.take_along_axis(
                xg, jnp.broadcast_to(
                    flat, (n, dg, cin // dg, flat.shape[-1])), axis=3)
            vals = vals.reshape(n, dg, cin // dg, k, ho, wo)
            return vals * inb[:, :, None].astype(xv.dtype)

        wy = wy[:, :, None]; wx = wx[:, :, None]
        sampled = (at(y0i, x0i) * (1 - wy) * (1 - wx)
                   + at(y0i, x0i + 1) * (1 - wy) * wx
                   + at(y0i + 1, x0i) * wy * (1 - wx)
                   + at(y0i + 1, x0i + 1) * wy * wx)
        if mv is not None:                               # v2 modulation
            m = mv.reshape(n, dg, 1, k, ho, wo).astype(xv.dtype)
            sampled = sampled * m
        sampled = sampled.reshape(n, cin, k, ho, wo)

        # grouped contraction: [N, g, Cin/g, K, Ho, Wo] x
        #                      [g, Cout/g, Cin/g, K] -> [N, g, Cout/g, ...]
        sg = sampled.reshape(n, groups, cin // groups, k, ho, wo)
        wg = wv.reshape(groups, cout // groups, cin_g, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", sg, wg)
        out = out.reshape(n, cout, ho, wo)
        if bv is not None:
            out = out + bv.reshape(1, cout, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(f, *args, name="deform_conv2d")
