"""Spatial sampling ops: affine_grid + grid_sample.

TPU-native equivalents of the reference's spatial sampler pair
(reference: paddle/fluid/operators/affine_grid_op.cc,
operators/grid_sampler_op.cc + python/paddle/nn/functional/vision.py:60,152).
Everything is expressed as vectorized gathers over a flattened H*W axis —
no scalar loops, fully jittable and differentiable (the reference's CPU/GPU
kernels hand-roll the 4-corner interpolation and its backward; here jax AD
derives the scatter-add backward automatically).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor._helper import apply, unwrap

__all__ = ["affine_grid", "grid_sample"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a sampling grid [N, H, W, 2] from batched affine transforms
    ``theta`` [N, 2, 3] (reference: nn/functional/vision.py:60)."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().reshape(-1)]
    n, _, h, w = [int(v) for v in out_shape]

    def f(th):
        dt = th.dtype
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w, dtype=dt) if w > 1 else \
                jnp.zeros((1,), dt)
            ys = jnp.linspace(-1.0, 1.0, h, dtype=dt) if h > 1 else \
                jnp.zeros((1,), dt)
        else:
            xs = (2.0 * jnp.arange(w, dtype=dt) + 1.0) / w - 1.0
            ys = (2.0 * jnp.arange(h, dtype=dt) + 1.0) / h - 1.0
        gx, gy = jnp.meshgrid(xs, ys)                   # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)       # [H, W, 3]
        # [N, H, W, 2] = base @ theta^T per batch
        return jnp.einsum("hwk,njk->nhwj", base, th)

    return apply(f, theta, name="affine_grid")


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(x, lo, span):
    """Continuous reflection of x into [lo, lo+span] (grid_sampler_op.h
    reflection semantics)."""
    if span <= 0:
        return jnp.zeros_like(x)
    d = jnp.abs(x - lo) % (2.0 * span)
    return lo + (span - jnp.abs(d - span))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N, C, H, W] at grid [N, Hg, Wg, 2] locations (normalized
    to [-1, 1]; grid[..., 0] indexes width, grid[..., 1] height).
    Reference: nn/functional/vision.py:152, operators/grid_sampler_op.cc."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest: {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode: {padding_mode}")

    def f(xv, gv):
        n, c, h, w = xv.shape
        gx = _unnormalize(gv[..., 0].astype(jnp.float32), w, align_corners)
        gy = _unnormalize(gv[..., 1].astype(jnp.float32), h, align_corners)

        if padding_mode == "reflection":
            if align_corners:
                gx = _reflect(gx, 0.0, float(w - 1))
                gy = _reflect(gy, 0.0, float(h - 1))
            else:
                gx = jnp.clip(_reflect(gx, -0.5, float(w)), 0, w - 1)
                gy = jnp.clip(_reflect(gy, -0.5, float(h)), 0, h - 1)
        elif padding_mode == "border":
            gx = jnp.clip(gx, 0, w - 1)
            gy = jnp.clip(gy, 0, h - 1)

        xf = xv.reshape(n, c, h * w)

        def gather(iy, ix):
            """xf values at integer (iy, ix) with zero outside."""
            inb = ((iy >= 0) & (iy < h) & (ix >= 0) & (ix < w))
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            idx = (iyc * w + ixc).reshape(n, 1, -1)      # [N, 1, Hg*Wg]
            vals = jnp.take_along_axis(
                xf, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
            vals = vals.reshape((n, c) + gv.shape[1:3])
            return vals * inb[:, None].astype(xv.dtype)

        if mode == "nearest":
            ix = jnp.round(gx).astype(jnp.int32)
            iy = jnp.round(gy).astype(jnp.int32)
            return gather(iy, ix)

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0).astype(xv.dtype)
        wy = (gy - y0).astype(xv.dtype)
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        wx = wx[:, None]
        wy = wy[:, None]
        return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy + v11 * wx * wy)

    return apply(f, x, grid, name="grid_sample")
