"""Linear-chain CRF family: sequence labeling (NER/tagging).

Reference: paddle/fluid/operators/linear_chain_crf_op.{cc,h} (forward
algorithm with hand-written backward), crf_decoding_op.h (Viterbi),
chunk_eval_op.h (chunk P/R/F1); python surface fluid/layers/linear_chain_crf
/ crf_decoding / chunk_eval.

TPU-native design: the reference computes alpha recursions in normalized
probability space with a hand-written gradient kernel; here both the
forward algorithm and Viterbi are ``lax.scan`` over the time axis in LOG
space (numerically equivalent to the reference's per-step L1
normalization), jittable with static [B, T, D] shapes and masked by the
per-row ``length`` — and the backward pass is plain jax AD through the
scan, no custom gradient needed. chunk_eval is a host metric (the
reference's kernel is CPU-only too).

Transition layout matches the reference exactly (linear_chain_crf_op.h
ForwardOneSequence): ``transition`` is [D+2, D]; row 0 = start weights,
row 1 = end weights, rows 2.. = W[j, i] score of tag j -> tag i.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...tensor._helper import apply, unwrap

__all__ = ["linear_chain_crf", "crf_decoding", "chunk_eval"]


def linear_chain_crf(input, label, transition, length=None, name=None):  # noqa: A002
    """Negative log-likelihood of tag sequences under a linear-chain CRF
    (reference: linear_chain_crf_op.h ForwardOneSequence returns -ll).

    input: emissions [B, T, D] (padded); label: [B, T] int; transition:
    [D+2, D]; length: [B]. Returns nll [B, 1]. Differentiable w.r.t.
    input and transition (the reference ships a hand-written grad kernel;
    jax AD through the scan is the TPU equivalent).
    """
    if length is None:
        raise ValueError("linear_chain_crf: dense-ragged form requires "
                         "`length`")

    def f(x, lbl, w, lv):
        b, t, d = x.shape
        lv = lv.reshape(-1)
        w_start, w_end, trans = w[0], w[1], w[2:]     # [D],[D],[D,D]
        lbl = lbl.reshape(b, t).astype(jnp.int32)

        # --- log partition via forward algorithm (log space) ---
        alpha0 = w_start[None, :] + x[:, 0]           # [B, D]

        def step(alpha, k):
            nxt = jax.nn.logsumexp(
                alpha[:, :, None] + trans[None, :, :], axis=1) + x[:, k]
            alive = (k < lv)[:, None]
            return jnp.where(alive, nxt, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t)) \
            if t > 1 else (alpha0, None)
        logz = jax.nn.logsumexp(alpha + w_end[None, :], axis=1)

        # --- gold path score ---
        l0 = lbl[:, 0]
        score = w_start[l0] + jnp.take_along_axis(
            x[:, 0], l0[:, None], axis=1)[:, 0]
        if t > 1:
            prev = lbl[:, :-1]
            cur = lbl[:, 1:]
            emit = jnp.take_along_axis(x[:, 1:], cur[..., None],
                                       axis=2)[..., 0]       # [B, T-1]
            tr = trans[prev, cur]                             # [B, T-1]
            k = jnp.arange(1, t)[None, :]
            alive = k < lv[:, None]
            score = score + jnp.sum(jnp.where(alive, emit + tr, 0.0),
                                    axis=1)
        last = jnp.clip(lv - 1, 0, t - 1)
        last_lbl = jnp.take_along_axis(lbl, last[:, None], axis=1)[:, 0]
        score = score + w_end[last_lbl]
        return (logz - score)[:, None]                # nll [B, 1]

    return apply(f, input, label, transition, length,
                 name="linear_chain_crf")


def crf_decoding(input, transition, length=None, label=None, name=None):  # noqa: A002
    """Viterbi decode (reference: crf_decoding_op.h Decode): returns the
    best tag path [B, T] (zeros past each row's length). With ``label``
    given, returns per-position 0/1 correctness instead (the reference's
    evaluation mode). Dtype deviation: int32, not the reference's int64 —
    jax's default x64-disabled config makes int32 the native TPU index
    dtype."""
    if length is None:
        raise ValueError("crf_decoding: dense-ragged form requires "
                         "`length`")

    def f(x, w, lv, *rest):
        b, t, d = x.shape
        lv = lv.reshape(-1)
        w_start, w_end, trans = w[0], w[1], w[2:]

        alpha0 = w_start[None, :] + x[:, 0]

        def fwd(alpha, k):
            scores = alpha[:, :, None] + trans[None, :, :]   # [B, D, D]
            best = jnp.max(scores, axis=1) + x[:, k]
            track = jnp.argmax(scores, axis=1)               # [B, D]
            alive = (k < lv)[:, None]
            return (jnp.where(alive, best, alpha),
                    jnp.where(alive, track, -1))

        if t > 1:
            alpha, tracks = jax.lax.scan(fwd, alpha0,
                                         jnp.arange(1, t))
            tracks = jnp.moveaxis(tracks, 0, 1)              # [B, T-1, D]
        else:
            alpha = alpha0
            tracks = jnp.zeros((b, 0, d), jnp.int32)
        last_tag = jnp.argmax(alpha + w_end[None, :], axis=1)  # [B]

        # backtrace from each row's last valid position: walking the
        # track table backwards, holding the tag until k < len-1
        def bwd(tag, k):
            trk = tracks[:, k]                               # [B, D]
            prev = jnp.take_along_axis(trk, tag[:, None], axis=1)[:, 0]
            inside = k < (lv - 1)
            new_tag = jnp.where(inside, prev, tag)
            # emit the tag AT position k (tag of step k is new_tag when
            # k+1 is inside the sequence, else still the last tag)
            return new_tag, new_tag

        if t > 1:
            _, rev = jax.lax.scan(bwd, last_tag,
                                  jnp.arange(t - 2, -1, -1))
            path = jnp.concatenate(
                [jnp.flip(jnp.moveaxis(rev, 0, 1), axis=1),
                 last_tag[:, None]], axis=1)                 # [B, T]
        else:
            path = last_tag[:, None]
        # positions past the length emit 0; the "last tag" must sit at
        # index len-1, not t-1: roll each row's tail into place
        kidx = jnp.arange(t)[None, :]
        # path currently has last_tag at column t-1 and the inside walk
        # before it. For rows with lv < t the backtrace above held
        # last_tag through the padded region, so the tag at len-1 is
        # already correct; just mask the pad tail.
        path = jnp.where(kidx < lv[:, None], path, 0)
        if rest:
            lbl = rest[0].reshape(b, t).astype(path.dtype)
            ok = (lbl == path).astype(jnp.int32)
            return jnp.where(kidx < lv[:, None], ok, 0)
        return path.astype(jnp.int32)

    args = (input, transition, length) + \
        ((label,) if label is not None else ())
    return apply(f, *args, differentiable=False, name="crf_decoding")


def _get_segments(tags, num_chunk_types, num_tag_types, tag_begin,
                  tag_inside, tag_end, tag_single):
    """Chunk segmentation (reference: chunk_eval_op.h GetSegments with
    ChunkBegin/ChunkEnd predicates)."""
    other = num_chunk_types
    segments = []
    in_chunk = False
    chunk_start = 0
    tag, typ = -1, other

    def chunk_end(pt, pty, t, ty):
        if pty == other:
            return False
        if ty == other or ty != pty:
            return True
        if pt == tag_begin or pt == tag_inside:
            return t == tag_begin or t == tag_single
        return pt == tag_end or pt == tag_single

    def chunk_begin(pt, pty, t, ty):
        if pty == other:
            return ty != other
        if ty == other:
            return False
        if ty != pty:
            return True
        if t == tag_begin or t == tag_single:
            return True
        if t == tag_inside or t == tag_end:
            return pt == tag_end or pt == tag_single
        return False

    for i, lab in enumerate(tags):
        pt, pty = tag, typ
        tag = int(lab) % num_tag_types
        typ = int(lab) // num_tag_types
        if in_chunk and chunk_end(pt, pty, tag, typ):
            segments.append((chunk_start, i - 1, pty))
            in_chunk = False
        if chunk_begin(pt, pty, tag, typ):
            chunk_start = i
            in_chunk = True
    if in_chunk:
        segments.append((chunk_start, len(tags) - 1, typ))
    return segments


_SCHEMES = {
    # scheme: (num_tag_types, begin, inside, end, single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, length=None, name=None):
    """Chunk-level precision/recall/F1 (reference: chunk_eval_op.h;
    python fluid/layers/nn.py chunk_eval). Host metric op.

    input/label: [B, T] int (padded) with ``length`` [B], or 1-D packed.
    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks) — scalars, like the reference.
    """
    if chunk_scheme not in _SCHEMES:
        raise ValueError(f"chunk_eval: unknown chunk_scheme "
                         f"{chunk_scheme!r}")
    ntag, tb, ti, te, ts = _SCHEMES[chunk_scheme]
    excluded = set(excluded_chunk_types or ())
    inf = np.asarray(unwrap(input)).astype(np.int64)
    lab = np.asarray(unwrap(label)).astype(np.int64)
    if inf.ndim == 1:
        inf, lab = inf[None], lab[None]
    lens = (np.asarray(unwrap(length)).astype(np.int64).reshape(-1)
            if length is not None
            else np.full((inf.shape[0],), inf.shape[1], np.int64))

    n_inf = n_lab = n_cor = 0
    for b in range(inf.shape[0]):
        li = int(lens[b])
        seg_i = [s for s in _get_segments(inf[b, :li], num_chunk_types,
                                          ntag, tb, ti, te, ts)
                 if s[2] not in excluded]
        seg_l = [s for s in _get_segments(lab[b, :li], num_chunk_types,
                                          ntag, tb, ti, te, ts)
                 if s[2] not in excluded]
        n_inf += len(seg_i)
        n_lab += len(seg_l)
        n_cor += len(set(seg_i) & set(seg_l))
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    mk = lambda v, dt: Tensor(jnp.asarray(np.asarray(v, dt)))  # noqa: E731
    return (mk(prec, np.float32), mk(rec, np.float32),
            mk(f1, np.float32), mk(n_inf, np.int64),
            mk(n_lab, np.int64), mk(n_cor, np.int64))
